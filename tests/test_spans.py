"""Per-job distributed tracing + SLO attribution plane (PR 12).

Covers the ISSUE-12 acceptance surface:

- trace-context propagation: a trace id minted at ``spool.submit``
  (additive ``m4t-job/1`` field) reaches every plane — span records,
  audit records, done records, rank environments, and (armed-only)
  the emission/exec/latency/flight-recorder telemetry records;
- the unarmed telemetry record schema stays byte-identical to PR 11
  (drift-pin test) and ``serving.jsonl`` stays backward-readable;
- the span model: ``queued -> [verify] -> dispatch -> run -> result``
  chains with attempt/spawn/warm_dispatch children, verified gapless
  for every terminal job id (the span-chain completeness property);
- the merged serving trace: (job, rank)-keyed process tracks (the
  pid-collision fix), per-tenant sort-index grouping, and a golden
  file pinning the exact export for a fixed input
  (``tests/data/serve_trace_golden.json``; regen with
  ``python -m tests.test_spans --regen``);
- the SLO plane: config parsing, per-tenant percentile evaluation,
  deduped breach verdicts in the PR 8 shape, comm-dominant breaches
  emitting ``retune`` events with real plan keys, stage attribution
  narration through the doctor;
- e2e: a 2-rank warm-pool serve over 3 jobs whose emission records
  carry the submitting job's id, every job with a complete span
  chain in one merged Perfetto trace, and an injected slowdown
  producing an SLO breach whose narration names the dominant stage.
"""

import json
import os
import subprocess
import sys

import pytest

from mpi4jax_tpu.observability import events, spans, trace
from mpi4jax_tpu.serving import export as sexport
from mpi4jax_tpu.serving import slo as slo_mod
from mpi4jax_tpu.serving.pool import WorkerPool
from mpi4jax_tpu.serving.server import Server
from mpi4jax_tpu.serving.spool import JobSpecError, Spool, parse_job

pytestmark = [pytest.mark.tracing, pytest.mark.serving]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_GOLDEN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "data", "serve_trace_golden.json",
)


def _run_cli(module, *argv):
    return subprocess.run(
        [sys.executable, "-m", module, *argv],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


def _stub_server(spool, runner, **kw):
    kw.setdefault("nproc", 1)
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("log", lambda msg: None)
    return Server(spool, runner=runner, **kw)


# ---------------------------------------------------------------------
# trace-context propagation
# ---------------------------------------------------------------------


def test_submit_mints_trace_id(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    r = spool.submit({"id": "j1", "cmd": ["-c", "pass"]})
    assert r["status"] == "queued" and r["trace"].startswith("tr-")
    (spec,) = spool.pending()
    assert spec.trace == r["trace"]
    # the submitted audit record carries it too
    (sub,) = [x for x in spool.audit_records()
              if x["event"] == "submitted"]
    assert sub["trace"] == r["trace"]


def test_explicit_trace_id_round_trips(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    r = spool.submit({
        "id": "j1", "cmd": ["-c", "pass"], "trace": "upstream-7f3a",
    })
    assert r["trace"] == "upstream-7f3a"
    (spec,) = spool.pending()
    assert spec.trace == "upstream-7f3a"
    assert spec.to_json()["trace"] == "upstream-7f3a"


def test_invalid_trace_id_rejected():
    with pytest.raises(JobSpecError, match="trace"):
        parse_job({"cmd": ["x"], "trace": "no spaces"})
    with pytest.raises(JobSpecError, match="trace"):
        parse_job({"cmd": ["x"], "trace": 7})


def test_trace_reaches_rank_env():
    from mpi4jax_tpu import launch

    env = launch.rank_env(
        0, 2, shm_name="/x", shm_gen=1, trace_id="tr-abc",
        job_id="j9",
    )
    assert env["M4T_TRACE_ID"] == "tr-abc"
    assert env["M4T_JOB_ID"] == "j9"
    bare = launch.rank_env(0, 2, shm_name="/x", shm_gen=1)
    assert "M4T_TRACE_ID" not in bare and "M4T_JOB_ID" not in bare


def test_done_record_and_runner_args_carry_trace(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "j1", "cmd": ["-c", "pass"]})
    seen = {}

    def runner(spec, world, events_dir, attempt, resume_step):
        seen["spec"] = spec
        return 0, []

    server = _stub_server(spool, runner, max_jobs=1)
    assert server.serve() == 0
    (done,) = spool.done()
    assert done["trace"] and done["trace"] == seen["spec"].trace
    # and the launch-path args namespace would export it
    args = server._world_args(seen["spec"], 1)
    assert args.trace_id == done["trace"]
    assert args.job_id == "j1"


# ---------------------------------------------------------------------
# armed-only telemetry stamping + unarmed drift pin
# ---------------------------------------------------------------------

#: the PR 11 unarmed record schemas, pinned literally: adding a field
#: to the *unarmed* path is a breaking change for every downstream
#: reader and must be an intentional, reviewed edit of these pins
UNARMED_EMISSION_KEYS = {
    "kind", "cid", "op", "bytes", "dtype", "axes", "world",
    "annotation", "shape", "t", "seq", "op_seq",
}
UNARMED_RECORDER_KEYS = {
    "kind", "seq", "op", "cid", "bytes", "dtype", "shape", "axes",
    "world", "t",
}


@pytest.fixture
def clean_telemetry(monkeypatch):
    from mpi4jax_tpu import observability as obs
    from mpi4jax_tpu.observability import metrics as metrics_mod

    monkeypatch.delenv("M4T_TRACE_ID", raising=False)
    monkeypatch.delenv("M4T_JOB_ID", raising=False)
    prev_enabled = metrics_mod._enabled
    prev_sink = events.get_sink()
    obs.reset()
    obs.enable()
    yield obs
    obs.reset()
    metrics_mod._enabled = prev_enabled
    events._sink = prev_sink


def test_unarmed_emission_schema_drift_pin(clean_telemetry):
    rec = clean_telemetry.registry.record_emission(
        "AllReduce", nbytes=64, dtype="float32", axes=("ranks",),
        world=2, cid="c1",
    )
    assert set(rec) == UNARMED_EMISSION_KEYS, sorted(rec)


def test_armed_emission_carries_trace_and_job(clean_telemetry):
    rec = clean_telemetry.registry.record_emission(
        "AllReduce", nbytes=64, dtype="float32", axes=("ranks",),
        world=2, cid="c1", trace="tr-1", job="j1",
    )
    assert set(rec) == UNARMED_EMISSION_KEYS | {"trace", "job"}
    assert rec["trace"] == "tr-1" and rec["job"] == "j1"


def test_unarmed_recorder_schema_drift_pin():
    from mpi4jax_tpu.observability.recorder import FlightRecorder

    fr = FlightRecorder(capacity=4)
    fr.enable(True)
    fr.record("AllReduce", cid="c1", nbytes=64, dtype="float32",
              axes=("ranks",), world=2)
    (entry,) = fr.snapshot()
    assert set(entry) == UNARMED_RECORDER_KEYS, sorted(entry)
    fr.reset()
    fr.record("AllReduce", cid="c2", nbytes=64, trace="tr-1", job="j1")
    (entry,) = fr.snapshot()
    assert entry["trace"] == "tr-1" and entry["job"] == "j1"


def test_emission_env_arming_through_real_op(
    clean_telemetry, tmp_path, monkeypatch
):
    """The ops/_core.py prologue reads M4T_TRACE_ID/M4T_JOB_ID per
    emission (the warm pool swaps them between in-process jobs), and
    exec/latency events inherit the stamp from their emission."""
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.observability import metrics as metrics_mod

    sink = str(tmp_path / "events.jsonl")
    events.set_sink(sink)
    m4t.allreduce(jnp.ones(4))
    monkeypatch.setenv("M4T_TRACE_ID", "tr-armed")
    monkeypatch.setenv("M4T_JOB_ID", "job-armed")
    m4t.allreduce(jnp.ones(8))
    monkeypatch.delenv("M4T_TRACE_ID")
    monkeypatch.delenv("M4T_JOB_ID")
    m4t.allreduce(jnp.ones(16))
    recs = [r for r in events.read(sink) if r["kind"] == "emission"]
    assert len(recs) == 3
    assert "trace" not in recs[0] and "job" not in recs[0]
    assert recs[1]["trace"] == "tr-armed"
    assert recs[1]["job"] == "job-armed"
    assert "trace" not in recs[2]
    # exec/latency inherit from the emission record (armed only)
    armed = recs[1]
    metrics_mod.registry.mark_runtime_start(armed["cid"])
    metrics_mod.registry.mark_runtime_end(armed["cid"], armed["op"])
    bare = recs[2]
    metrics_mod.registry.mark_runtime_start(bare["cid"])
    metrics_mod.registry.mark_runtime_end(bare["cid"], bare["op"])
    by_kind = {}
    for r in events.read(sink):
        by_kind.setdefault(r["kind"], []).append(r)
    execs = {r.get("cid"): r for r in by_kind["exec"]}
    lats = {r.get("cid"): r for r in by_kind["latency"]}
    assert execs[armed["cid"]]["trace"] == "tr-armed"
    assert lats[armed["cid"]]["job"] == "job-armed"
    assert "trace" not in execs[bare["cid"]]
    assert "trace" not in lats[bare["cid"]]
    events.set_sink(None)


# ---------------------------------------------------------------------
# span chains
# ---------------------------------------------------------------------


def test_span_chain_completeness_property(tmp_path):
    """Every terminal job id in serving.jsonl has a gapless
    queued -> ... -> result chain — including failed and retried
    jobs."""
    spool = Spool(str(tmp_path / "sp"))
    for obj in (
        {"id": "ok", "tenant": "a", "cmd": ["-c", "pass"]},
        {"id": "flaky", "tenant": "b", "cmd": ["-c", "pass"],
         "retries": 2, "backoff_s": 0.0},
        {"id": "bad", "tenant": "a", "cmd": ["-c", "pass"],
         "retries": 1, "backoff_s": 0.0},
    ):
        assert spool.submit(obj)["status"] == "queued"

    def runner(spec, world, events_dir, attempt, resume_step):
        if spec.id == "bad":
            return 1, []
        if spec.id == "flaky" and attempt < 2:
            return 1, []
        return 0, []

    server = _stub_server(spool, runner, max_jobs=3)
    assert server.serve() == 0
    terminals = spans.terminal_jobs(spool.audit_records())
    assert sorted(terminals) == ["bad", "flaky", "ok"]
    verdicts = spans.verify_chains(spool.span_records(), jobs=terminals)
    for job, v in verdicts.items():
        assert v["complete"], (job, v)
        assert v["trace"], job
    # retries surface as attempt children inside run
    flaky = [s["span"] for s in spans.chains(spool.span_records())
             ["flaky"] if s["span"].startswith("attempt")]
    assert flaky == ["attempt0", "attempt1", "attempt2"]
    # a job that never wrote spans is named, not silently passed
    missing = spans.verify_chains(
        spool.span_records(), jobs=["ghost"]
    )["ghost"]
    assert not missing["complete"]
    assert missing["missing"] == list(spans.REQUIRED)


def test_verify_gate_emits_verify_span(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "v1", "cmd": ["-c", "pass"], "verify": True})
    server = _stub_server(
        spool, lambda *a: (0, []), max_jobs=1,
        verify_fn=lambda spec, world: True,
    )
    assert server.serve() == 0
    chain = [s["span"] for s in spans.chains(spool.span_records())["v1"]
             if s["span"] in spans.CHAIN]
    assert chain == ["queued", "verify", "dispatch", "run", "result"]
    v = spans.verify_chains(spool.span_records())["v1"]
    assert v["complete"], v


def test_rejected_job_keeps_queued_and_verify_spans(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "nope", "cmd": ["-c", "pass"], "verify": True})
    server = _stub_server(
        spool, lambda *a: (0, []), max_jobs=1,
        verify_fn=lambda spec, world: False,
    )
    assert server.serve() == 0
    (done,) = spool.done()
    assert done["outcome"] == "rejected"
    got = [s["span"] for s in spool.span_records()]
    assert got == ["queued", "verify"]
    # rejected jobs are not terminal-chain material
    assert spans.terminal_jobs(spool.audit_records()) == []


def test_serving_audit_stays_backward_readable(tmp_path):
    """Span records ride in serving.jsonl without disturbing any
    PR 10/11 reader: audit_records() filters them out and the doctor
    timeline still narrates."""
    from mpi4jax_tpu.observability import doctor

    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "j1", "cmd": ["-c", "pass"]})
    server = _stub_server(spool, lambda *a: (0, []), max_jobs=1)
    assert server.serve() == 0
    assert spool.span_records()
    for rec in spool.audit_records():
        assert rec["kind"] == "serving"
        assert rec.get("event") != "span"
    timeline = doctor.format_serving_timeline(
        doctor.load_serving_audit([spool.root])
    )
    assert "completed: job j1" in timeline


# ---------------------------------------------------------------------
# merged serving trace (trace --serve)
# ---------------------------------------------------------------------


def synthetic_serve_world():
    """Fixed input for the golden/schema tests (all timestamps
    pinned; regenerate the golden with
    ``python -m tests.test_spans`` after intentional changes).
    Two tenants, two jobs, both with a rank 0 — the pid-collision
    surface."""
    def emission(rank, seq, job, tr, t, nbytes=16):
        return {
            "kind": "emission", "rank": rank, "seq": seq,
            "op": "AllReduce", "shape": [8], "dtype": "float32",
            "axes": ["ranks"], "world": 2, "bytes": nbytes,
            "cid": f"c{job}{rank}{seq}", "t": t, "trace": tr,
            "job": job,
        }

    def chain(job, tr, tenant, t):
        return [
            spans.span_record("queued", job=job, t0=t, t1=t + 1.0,
                              trace=tr, tenant=tenant),
            spans.span_record("dispatch", job=job, t0=t + 1.0,
                              t1=t + 1.5, trace=tr, tenant=tenant),
            spans.span_record("run", job=job, t0=t + 1.5, t1=t + 4.0,
                              trace=tr, tenant=tenant),
            spans.span_record("attempt0", job=job, t0=t + 1.5,
                              t1=t + 4.0, trace=tr, tenant=tenant,
                              attempt=0, exit_code=0),
            spans.span_record("result", job=job, t0=t + 4.0,
                              t1=t + 4.1, trace=tr, tenant=tenant),
        ]

    return {
        "jobs": [
            {
                "id": "jA", "tenant": "alpha", "trace": "tr-a",
                "spans": chain("jA", "tr-a", "alpha", 100.0),
                "by_rank": {
                    0: [emission(0, 1, "jA", "tr-a", 102.0),
                        {"kind": "latency", "rank": 0,
                         "op": "AllReduce", "seconds": 0.5,
                         "t": 102.6, "seq": 1, "cid": "cjA01",
                         "trace": "tr-a", "job": "jA"}],
                    1: [emission(1, 1, "jA", "tr-a", 102.1)],
                },
            },
            {
                "id": "jB", "tenant": "beta", "trace": "tr-b",
                "spans": chain("jB", "tr-b", "beta", 101.0),
                "by_rank": {
                    0: [emission(0, 1, "jB", "tr-b", 103.0,
                                 nbytes=32)],
                },
            },
        ],
    }


def test_serve_trace_keys_tracks_by_job_and_rank():
    obj = trace.build_serve_trace(synthetic_serve_world())
    names = {
        ev["pid"]: ev["args"]["name"]
        for ev in obj["traceEvents"]
        if ev["name"] == "process_name"
    }
    # the collision fix: jA rank 0 and jB rank 0 are distinct tracks
    assert names[1] == "alpha/jA · rank 0"
    assert names[101] == "beta/jB · rank 0"
    assert names[0] == "alpha/jA · lifecycle"
    # emission instants landed on their own job's track
    pids = {}
    for ev in obj["traceEvents"]:
        if ev["ph"] == "i" and ev["name"] == "AllReduce" and (
            ev["args"].get("trace")
        ):
            pids.setdefault(ev["args"]["job"], set()).add(ev["pid"])
    assert pids == {"jA": {1, 2}, "jB": {101}}
    # every track carries stable sort-index metadata
    sort_pids = {
        ev["pid"] for ev in obj["traceEvents"]
        if ev["name"] == "process_sort_index"
    }
    assert sort_pids == set(names)
    # lifecycle spans are duration slices on the job track
    run_slices = [
        ev for ev in obj["traceEvents"]
        if ev["ph"] == "X" and ev["name"] == "run"
    ]
    assert {ev["pid"] for ev in run_slices} == {0, 100}
    for ev in run_slices:
        assert ev["dur"] == pytest.approx(2.5e6)
    # collective instants fall inside their job's run span window
    for ev in obj["traceEvents"]:
        if ev["ph"] == "i" and ev["name"] == "AllReduce":
            base = (ev["pid"] // trace.JOB_PID_STRIDE) * (
                trace.JOB_PID_STRIDE
            )
            (run,) = [r for r in run_slices if r["pid"] == base]
            assert run["ts"] <= ev["ts"] <= run["ts"] + run["dur"]


def test_serve_trace_golden_file():
    """The exact merged-serving export for the fixed input is pinned —
    any schema drift must be an intentional, reviewed change."""
    obj = trace.build_serve_trace(synthetic_serve_world())
    normalized = json.loads(json.dumps(obj, sort_keys=True))
    with open(SERVE_GOLDEN) as f:
        golden = json.load(f)
    assert normalized == golden


def test_single_run_trace_keeps_rank_pids_with_sort_index():
    by_rank = {
        0: [{"kind": "emission", "rank": 0, "seq": 1,
             "op": "AllReduce", "shape": [8], "dtype": "float32",
             "axes": ["ranks"], "world": 2, "bytes": 16, "cid": "c1",
             "t": 100.0}],
        1: [{"kind": "emission", "rank": 1, "seq": 1,
             "op": "AllReduce", "shape": [8], "dtype": "float32",
             "axes": ["ranks"], "world": 2, "bytes": 16, "cid": "c2",
             "t": 100.1}],
    }
    obj = trace.build_trace(by_rank)
    names = {
        (ev["pid"], ev["args"]["name"])
        for ev in obj["traceEvents"] if ev["name"] == "process_name"
    }
    assert names == {(0, "rank 0"), (1, "rank 1")}
    sorts = {
        ev["pid"]: ev["args"]["sort_index"]
        for ev in obj["traceEvents"]
        if ev["name"] == "process_sort_index"
    }
    assert sorts == {0: 0, 1: 1}


def test_trace_serve_cli_round_trip(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    for i in range(2):
        spool.submit({"id": f"j{i}", "tenant": "t", "cmd": ["-c", "x"]})
    server = _stub_server(spool, lambda *a: (0, []), max_jobs=2)
    assert server.serve() == 0
    out = str(tmp_path / "serve.json")
    res = _run_cli(
        "mpi4jax_tpu.observability.trace", "--serve", spool.root,
        "-o", out,
    )
    assert res.returncode == 0, res.stderr
    obj = json.load(open(out))
    jobs = {m["job"] for m in obj["otherData"]["jobs"]}
    assert jobs == {"j0", "j1"}
    # an empty spool is exit 2, not a traceback
    res = _run_cli(
        "mpi4jax_tpu.observability.trace", "--serve",
        str(tmp_path / "empty"), "-o", out,
    )
    assert res.returncode == 2


# ---------------------------------------------------------------------
# SLO plane
# ---------------------------------------------------------------------


def test_parse_slo_forms():
    c = slo_mod.parse_slo("p99_latency_s=2.0, error_rate=0.05")
    assert c["default"] == {"p99_latency_s": 2.0, "error_rate": 0.05}
    c = slo_mod.parse_slo({"default": {"p50_latency_s": 1.0},
                           "tenants": {"bulk": {"p50_latency_s": 9.0}}})
    assert slo_mod.objectives_for(c, "bulk") == {"p50_latency_s": 9.0}
    assert slo_mod.objectives_for(c, "other") == {"p50_latency_s": 1.0}
    c = slo_mod.parse_slo('{"p90_queue_wait_s": 0.5}')
    assert c["default"] == {"p90_queue_wait_s": 0.5}


def test_parse_slo_file(tmp_path):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({"tenants": {"a": {"error_rate": 0.1}}}))
    c = slo_mod.parse_slo(str(path))
    assert slo_mod.objectives_for(c, "a") == {"error_rate": 0.1}


@pytest.mark.parametrize("bad, needle", [
    ("p99_latency_s", "objective=threshold"),
    ("p99_latency_s=fast", "not a number"),
    ("p99_sparkle_s=1", "unknown objective"),
    ('{"default": {}, "oops": {}}', "unknown section"),
    ("", "no objectives"),
    ('{"p99_latency_s": -1}', "non-negative"),
])
def test_parse_slo_rejects(bad, needle):
    with pytest.raises(slo_mod.SLOError, match=needle):
        slo_mod.parse_slo(bad)


def _served_spool(tmp_path, runner, jobs, **kw):
    spool = Spool(str(tmp_path / "sp"))
    for obj in jobs:
        assert spool.submit(obj)["status"] == "queued"
    server = _stub_server(spool, runner, max_jobs=len(jobs), **kw)
    server.serve()
    return spool


def test_slo_breach_verdict_dedupe_and_narration(tmp_path):
    import time as _time

    def runner(spec, world, events_dir, attempt, resume):
        if spec.id == "slow":
            _time.sleep(0.25)
        return 0, []

    spool = _served_spool(tmp_path, runner, [
        {"id": "fast", "tenant": "a", "cmd": ["-c", "x"]},
        {"id": "slow", "tenant": "a", "cmd": ["-c", "x"]},
    ])
    config = slo_mod.parse_slo("p99_latency_s=0.1")
    watch = slo_mod.SLOWatch(spool, config)
    new = watch.check()
    assert len(new) == 1
    breach = new[0]
    assert breach["tenant"] == "a" and breach["job"] == "slow"
    assert breach["observed"] > 0.1
    assert breach["dominant_stage"] == "compute"  # stub runner sleeps
    assert breach["dominant_share"] > 0.5
    # deduped: a second pass over the same evidence is silent
    assert watch.check() == []
    # the verdict event has the PR 8 shape and landed in slo.jsonl
    (rec,) = slo_mod.load_slo_verdicts([spool.root])
    assert rec["kind"] == "verdict" and rec["klass"] == "transient"
    assert rec["finding"]["kind"] == "slo_breach"
    # audited on serving.jsonl (backward-compatible extra event)
    assert any(r["event"] == "slo_breach"
               for r in spool.audit_records())
    text = slo_mod.narrate(breach)
    assert "job slow" in text and "compute-bound" in text


def test_slo_error_rate_objective(tmp_path):
    spool = _served_spool(
        tmp_path, lambda *a: (1, []),
        [{"id": "f1", "tenant": "x", "cmd": ["-c", "x"]}],
    )
    (breach,) = slo_mod.evaluate(
        spool, slo_mod.parse_slo("error_rate=0.5")
    )
    assert breach["objective"] == "error_rate"
    assert breach["observed"] == 1.0


def test_slo_queue_wait_dominant_names_capacity(tmp_path):
    """A breach dominated by queue-wait narrates 'capacity, not
    compute' — the doctor's headline for an under-provisioned mesh."""
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "jq", "tenant": "q", "cmd": ["-c", "x"]})
    # age the queue entry so queue_wait dwarfs the (instant) run
    (spec,) = spool.pending()
    import time as _time

    _time.sleep(0.3)
    server = _stub_server(spool, lambda *a: (0, []), max_jobs=1)
    assert server.serve() == 0
    (breach,) = slo_mod.evaluate(
        spool, slo_mod.parse_slo("p50_latency_s=0.05")
    )
    assert breach["dominant_stage"] == "queue_wait"
    assert "capacity, not compute" in slo_mod.narrate(breach)


def test_slo_comm_dominant_emits_retune_with_plan_keys(tmp_path):
    """When the dominant stage is communication, the breach emits a
    retune recommendation whose plan keys validate — the PR 8 loop's
    input, now fed by SLOs."""
    import time as _time

    from mpi4jax_tpu.planner import autotune

    def runner(spec, world, events_dir, attempt, resume):
        _time.sleep(0.4)  # a run window the comm samples can fill
        return 0, []

    spool = _served_spool(tmp_path, runner, [
        {"id": "commy", "tenant": "c", "cmd": ["-c", "x"]},
    ])
    (done,) = spool.done()
    tr = done["trace"]
    # fabricate the job's telemetry: emissions + latency samples that
    # account for most of the (span-recorded) run window
    run = [s for s in spool.span_records() if s["span"] == "run"][0]
    d = os.path.join(spool.root, "jobs", "commy", "attempt00")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "events-rank0.jsonl"), "w") as f:
        f.write(json.dumps({
            "kind": "emission", "rank": 0, "seq": 1,
            "op": "AllReduce", "shape": [1024], "dtype": "float32",
            "axes": ["ranks"], "world": 2, "bytes": 4096, "cid": "cc1",
            "t": run["t0"], "trace": tr, "job": "commy",
        }) + "\n")
        f.write(json.dumps({
            "kind": "latency", "rank": 0, "op": "AllReduce",
            "seconds": max(run["dur_s"] * 0.9, 1e-4), "seq": 1,
            "cid": "cc1", "t": run["t1"], "trace": tr, "job": "commy",
        }) + "\n")
    (breach,) = slo_mod.evaluate(
        spool, slo_mod.parse_slo("p99_latency_s=0.0")
    )
    assert breach["dominant_stage"] == "comm", breach
    watch = slo_mod.SLOWatch(
        spool, slo_mod.parse_slo("p99_latency_s=0.0")
    )
    assert watch.check()
    keys = autotune.keys_from_verdicts([spool.root], platform="cpu")
    assert keys and all("AllReduce|" in k for k in keys), keys


def test_slo_exporter_histograms(tmp_path):
    spool = _served_spool(tmp_path, lambda *a: (0, []), [
        {"id": f"j{i}", "tenant": "h", "cmd": ["-c", "x"]}
        for i in range(3)
    ])
    slo_mod.SLOWatch(
        spool, slo_mod.parse_slo("p99_latency_s=0.0")
    ).check()
    text = sexport.render_serving_metrics(
        sexport.serving_snapshot(spool)
    )
    assert text.endswith("# EOF\n")
    assert 'm4t_serve_job_latency_seconds_bucket{le="+Inf",tenant="h"} 3' in text
    assert 'm4t_serve_job_latency_seconds_count{tenant="h"} 3' in text
    assert 'm4t_serve_stage_seconds{quantile="p99",stage="queue_wait",tenant="h"}' in text
    assert 'm4t_serve_slo_breaches_total{objective="p99_latency_s",tenant="h"} 1' in text


def test_doctor_narrates_slo_breach(tmp_path):
    import time as _time

    def runner(spec, world, events_dir, attempt, resume):
        _time.sleep(0.2)
        return 0, []

    spool = _served_spool(tmp_path, runner, [
        {"id": "jd", "tenant": "d", "cmd": ["-c", "x"]},
    ])
    slo_mod.SLOWatch(
        spool, slo_mod.parse_slo("p99_latency_s=0.05")
    ).check()
    res = _run_cli("mpi4jax_tpu.observability.doctor", spool.root)
    assert "SLO breaches" in res.stdout, res.stdout
    assert "job jd" in res.stdout
    assert "compute-bound" in res.stdout


# ---------------------------------------------------------------------
# CLI + selftest
# ---------------------------------------------------------------------


def test_spans_cli_verdicts(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "j1", "cmd": ["-c", "x"]})
    server = _stub_server(spool, lambda *a: (0, []), max_jobs=1)
    assert server.serve() == 0
    res = _run_cli("mpi4jax_tpu.observability.spans", spool.root)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "j1: complete" in res.stdout
    res = _run_cli(
        "mpi4jax_tpu.observability.spans", spool.root, "--json"
    )
    assert json.loads(res.stdout)["j1"]["complete"] is True
    res = _run_cli(
        "mpi4jax_tpu.observability.spans", str(tmp_path / "none")
    )
    assert res.returncode == 2


def test_spans_selftest():
    res = _run_cli("mpi4jax_tpu.observability.spans", "--selftest")
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "spans selftest ok" in res.stdout


# ---------------------------------------------------------------------
# e2e: 2-rank warm pool, trace-id propagation, merged trace, SLO
# ---------------------------------------------------------------------


@pytest.mark.pool
def test_e2e_warm_pool_trace_and_slo(tmp_path):
    """ISSUE-12 acceptance: a 2-rank ``serve --warm`` over 3 jobs
    yields one merged Perfetto trace in which every submitted job has
    a complete span chain and its per-rank collective slices (warm
    workers' shared sinks attributed by trace id), and an injected
    slowdown produces an SLO breach whose narration names the
    dominant stage."""
    import time as _time

    spool = Spool(str(tmp_path / "sp"))
    pool = WorkerPool(
        os.path.join(spool.root, "pool"), 2, heartbeat_s=0.2,
        audit=spool.audit, span=spool.span, log=lambda m: None,
    )
    pool.start()
    try:
        # pre-warm (the loadgen convention) so queue wait measures
        # the queue, not the one-time worker import
        deadline = _time.monotonic() + 120.0
        while pool.idle_count() < 2:
            assert _time.monotonic() < deadline, "pool never ready"
            pool.check()
            _time.sleep(0.05)
        payload = ("import jax.numpy as jnp, mpi4jax_tpu as m4t; "
                   "m4t.allreduce(jnp.ones(8))")
        slow_payload = "import time; time.sleep(0.6); " + payload
        for i in range(3):
            body = slow_payload if i == 2 else payload
            r = spool.submit({
                "id": f"w{i}", "tenant": f"t{i % 2}",
                "cmd": ["-c", body],
            })
            assert r["status"] == "queued", r
        watch = slo_mod.SLOWatch(
            spool, slo_mod.parse_slo("p99_latency_s=0.5")
        )
        server = Server(
            spool, nproc=2, max_jobs=3, poll_s=0.02, pool=pool,
            slo=watch, log=lambda m: None,
        )
        rc = server.serve()
    finally:
        pool.stop(grace_s=2.0)
    assert rc == 0
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {f"w{i}": "completed" for i in range(3)}

    # every submitted job id has a complete, gapless span chain
    terminals = spans.terminal_jobs(spool.audit_records())
    assert sorted(terminals) == ["w0", "w1", "w2"]
    verdicts = spans.verify_chains(spool.span_records(), jobs=terminals)
    for job, v in verdicts.items():
        assert v["complete"], (job, v)
    # warm path: every chain has a warm_dispatch child
    by_job = spans.chains(spool.span_records())
    for job in terminals:
        assert any(s["span"] == "warm_dispatch" for s in by_job[job])

    # emission records in the shared pool sinks carry the submitting
    # job's id + trace (the 2-rank warm propagation assertion)
    traces = {r["id"]: r["trace"] for r in spool.done()}
    for job in terminals:
        by_rank = spans.collect_job_records(
            spool.root, job, traces[job]
        )
        ems = [
            r for recs in by_rank.values() for r in recs
            if r.get("kind") == "emission"
        ]
        assert ems, job
        assert all(e.get("job") == job for e in ems), (job, ems)
        assert all(e.get("trace") == traces[job] for e in ems)

    # one merged Perfetto trace holds every job, (job, rank)-keyed
    out = str(tmp_path / "serve_trace.json")
    assert trace.export_serve(spool.root, out) is not None
    obj = json.load(open(out))
    meta = {m["job"]: m for m in obj["otherData"]["jobs"]}
    assert set(meta) == {"w0", "w1", "w2"}
    for job, m in meta.items():
        assert m["ranks"], (job, "no per-rank slices in the trace")
        assert m["trace"] == traces[job]
    # each job's collective instants sit on its own pid block
    for ev in obj["traceEvents"]:
        if ev["ph"] == "i" and ev["args"].get("job"):
            base = meta[ev["args"]["job"]]["pid"]
            assert base < ev["pid"] < base + trace.JOB_PID_STRIDE

    # the injected slowdown breached the SLO with a named stage: the
    # slowed job dominates its tenant's p99 and its 0.6s sleep makes
    # the run stages (compute/comm) the story, not queue wait
    recs = slo_mod.load_slo_verdicts([spool.root])
    assert recs, "no SLO breach verdict"
    findings = {r["finding"].get("job"): r["finding"] for r in recs}
    assert "w2" in findings, findings
    assert findings["w2"]["dominant_stage"] in ("compute", "comm")
    res = _run_cli("mpi4jax_tpu.observability.doctor", spool.root)
    assert "SLO breaches" in res.stdout
    assert "job w2" in res.stdout


if __name__ == "__main__":
    # regenerate the golden serving trace after an intentional change
    obj = trace.build_serve_trace(synthetic_serve_world())
    with open(SERVE_GOLDEN, "w") as f:
        json.dump(json.loads(json.dumps(obj, sort_keys=True)), f,
                  indent=1, sort_keys=True)
    print(f"golden rewritten: {SERVE_GOLDEN}")
