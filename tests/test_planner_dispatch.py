"""Planner dispatch seam, jax half: backward compatibility (unarmed ==
the legacy heuristics, byte-identical lowering), the numerical parity
matrix across implementations x dtype x world size, zero overhead when
unarmed, telemetry impl stamps, and the armed static cost report.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import mpi4jax_tpu as m4t
from mpi4jax_tpu import config, observability as obs
from mpi4jax_tpu.parallel import spmd, world_mesh
from mpi4jax_tpu.planner import dispatch, plan as planmod

from tests.conftest import needs_supported_jax

pytestmark = pytest.mark.tuning

N = 8


@pytest.fixture(autouse=True)
def _clean_dispatch(monkeypatch):
    """Every test starts unarmed with no pins and a deterministic
    platform class; whatever it arms is torn down again."""
    monkeypatch.setattr(config, "PLATFORM_CLASS", "cpu")
    saved = (dispatch.active, dict(dispatch.pins))
    dispatch.disarm()
    dispatch.pins = {}
    yield
    dispatch.active, dispatch.pins = saved
    obs.disable()
    obs.reset()


def _mesh(world):
    return world_mesh(world)


def _lowered(world, payload_elems, dtype, op_fn=None):
    """Lowered StableHLO text of one collective over a world-sized
    mesh (fresh function object per call: jit caches per identity)."""
    mesh = _mesh(world)
    op_fn = op_fn or (lambda x: m4t.allreduce(x))
    fn = spmd(lambda x: op_fn(x), mesh=mesh)
    arr = jnp.zeros((world, payload_elems), dtype)
    return jax.jit(lambda x: fn(x)).lower(arr).as_text()


# ---------------------------------------------------------------------
# backward compatibility: unarmed == the legacy policy, byte for byte
# ---------------------------------------------------------------------


def test_unarmed_lowering_identical_to_explicit_hlo_pin():
    """Satellite pin: with no plan armed, the dispatch seam reproduces
    today's behavior byte-identically — the lowered program equals an
    explicit pin to the impl the legacy heuristic would have chosen."""
    baseline = _lowered(N, 4096, jnp.float32)
    assert "all_reduce" in baseline
    dispatch.set_pins("AllReduce:hlo")
    pinned = _lowered(N, 4096, jnp.float32)
    assert pinned == baseline


def test_unarmed_small_payload_stays_hlo_even_with_ring_flag(monkeypatch):
    # the legacy window's lower bound (1 MiB) is preserved verbatim:
    # latency-bound payloads stay on HLO AllReduce with the flag on
    monkeypatch.setattr(config, "PALLAS_RING", True)
    text = _lowered(N, 4096, jnp.float32)
    assert "all_reduce" in text


@needs_supported_jax
def test_unarmed_ring_flag_matches_ring_pin(monkeypatch):
    # with the opt-in flag and a >= 1 MiB payload the unarmed seam
    # routes the Pallas ring exactly as an explicit pin does
    monkeypatch.setattr(config, "PALLAS_RING", True)
    flagged = _lowered(N, 1 << 19, jnp.float32)  # 2 MiB f32
    monkeypatch.setattr(config, "PALLAS_RING", False)
    dispatch.set_pins("AllReduce:pallas_ring")
    pinned = _lowered(N, 1 << 19, jnp.float32)
    assert flagged == pinned
    assert "all_reduce" not in flagged


def test_unarmed_decisions_match_inline_legacy_predicate(monkeypatch):
    """The refactored default policy (planner/dispatch.default_impl)
    equals an independent reimplementation of the old
    ``_use_pallas_ring`` gate over a sweep of payloads/dtypes/flag
    states, evaluated at real emission sites."""
    from mpi4jax_tpu.comm import SUM, resolve_comm
    from mpi4jax_tpu.ops.pallas_ring import ring_gate

    seen = []

    def probe(x):
        comm = resolve_comm(None)
        legacy = SUM is SUM and ring_gate(
            x, comm, min_bytes=1 << 20, max_bytes=1 << 30
        )
        got = dispatch.select("AllReduce", x, SUM, comm).impl
        seen.append((x.size, str(x.dtype), config.PALLAS_RING,
                     "pallas_ring" if legacy else "hlo", got))
        return m4t.allreduce(x)

    mesh = _mesh(N)
    for flag in (False, True):
        monkeypatch.setattr(config, "PALLAS_RING", flag)
        for elems, dtype in [(64, jnp.float32), (1 << 19, jnp.float32),
                             (1 << 19, jnp.bfloat16), (1 << 19, jnp.int32)]:
            fn = spmd(lambda x: probe(x), mesh=mesh)
            jax.eval_shape(fn, jnp.zeros((N, elems), dtype))
    assert seen, "probe never ran"
    for elems, dtype, flag, want, got in seen:
        assert want == got, (elems, dtype, flag, want, got)


# ---------------------------------------------------------------------
# zero overhead unarmed (the fault-injection standard)
# ---------------------------------------------------------------------


def test_unarmed_records_carry_no_impl_fields():
    obs.enable()
    obs.reset()
    obs.flight_recorder.reset()

    def program(x):
        return m4t.allreduce(x * 2)

    spmd(program, mesh=_mesh(N))(jnp.ones((N, 16)))
    snap = obs.snapshot()
    assert snap["ops"]["AllReduce"]["emissions"] >= 1
    for rec in snap["emissions"]:
        assert "impl" not in rec and "plan" not in rec, rec
    for rec in obs.flight_recorder.snapshot():
        assert "impl" not in rec, rec


def test_armed_pin_stamps_impl_and_plan_into_telemetry():
    obs.enable()
    dispatch.set_pins("AllReduce:quantized")

    spmd(lambda x: m4t.allreduce(x), mesh=_mesh(N))(
        jnp.ones((N, 512), jnp.float32)
    )
    recs = [r for r in obs.snapshot()["emissions"]
            if r["op"] == "AllReduce"]
    assert recs and recs[-1]["impl"] == "quantized"
    assert recs[-1]["plan"] == "env"
    ring = [r for r in obs.flight_recorder.snapshot()
            if r["op"] == "AllReduce"]
    assert ring and ring[-1]["impl"] == "quantized"
    # perf attribution groups the armed emissions per impl
    result = obs.perf.attribute({0: obs.snapshot()["emissions"]})
    row = next(r for r in result["rows"] if r["op"] == "AllReduce")
    assert row["impl"] == "quantized"
    assert row["algorithm"].startswith("int8 ring")


# ---------------------------------------------------------------------
# numerical parity matrix: impl x dtype x world (satellite 2)
# ---------------------------------------------------------------------

_WORLDS = (2, 4, 8)
_DTYPES = ("float32", "bfloat16")


def _payload(world, dtype, seed=0):
    rng = np.random.RandomState(seed)
    # 777 elements: deliberately unaligned to every chunk/block size
    return rng.randn(world, 777).astype(np.float32) * 4.0, dtype


def _run_allreduce(world, arr, dtype):
    mesh = _mesh(world)
    fn = spmd(lambda x: m4t.allreduce(x), mesh=mesh)
    return np.asarray(
        fn(jnp.asarray(arr).astype(dtype)).astype(jnp.float32)
    )


@pytest.mark.parametrize("world", _WORLDS)
@pytest.mark.parametrize("dtype", _DTYPES)
@pytest.mark.parametrize("impl", ["hlo", "quantized", "pallas_ring"])
def test_allreduce_impl_parity(world, dtype, impl, request):
    """Every plannable AllReduce impl agrees with the exact reduction
    (allclose; bit-identical to unarmed for the hlo impl) at every
    world size — the dispatch seam must never change answers."""
    if impl == "pallas_ring":
        if world != jax.device_count():
            pytest.skip("ring kernels need the axis to span the mesh")
        request.applymarker(needs_supported_jax)
        from tests.conftest import JAX_BELOW_MINIMUM

        if JAX_BELOW_MINIMUM:
            pytest.skip("pallas ring needs jax >= minimum")
    arr, _ = _payload(world, dtype)
    baseline = _run_allreduce(world, arr, dtype)  # unarmed
    dispatch.set_pins(f"AllReduce:{impl}")
    out = _run_allreduce(world, arr, dtype)
    exact = arr.sum(axis=0)
    scale = max(np.abs(exact).max(), 1e-6)
    if impl == "hlo":
        # pinning the default must be *bit-identical* to unarmed
        np.testing.assert_array_equal(out, baseline)
    tol = 0.05 if impl == "quantized" else (0.02 if dtype == "bfloat16"
                                            else 1e-5)
    for r in range(world):
        err = np.abs(out[r] - exact).max() / scale
        assert err < tol, (impl, world, dtype, err)


@pytest.mark.parametrize("world,grid", [(4, (2, 2)), (8, (2, 4))])
@pytest.mark.parametrize("dtype", _DTYPES)
def test_allreduce_hierarchical_parity(world, grid, dtype):
    devs = np.asarray(jax.devices()[:world]).reshape(grid)
    mesh = Mesh(devs, ("a", "b"))
    comm = m4t.Comm(("a", "b"))
    arr, _ = _payload(world, dtype, seed=1)
    dispatch.set_pins("AllReduce:hierarchical")
    fn = shard_map(
        lambda x: m4t.allreduce(x, comm=comm), mesh=mesh,
        in_specs=P(("a", "b")), out_specs=P(("a", "b")), check_rep=False,
    )
    out = np.asarray(fn(jnp.asarray(arr).astype(dtype)).astype(jnp.float32))
    # the lowering really is two-level: a reduce-scatter appears
    text = jax.jit(fn).lower(
        jnp.asarray(arr).astype(dtype)
    ).as_text()
    assert "reduce_scatter" in text or "psum_scatter" in text, (
        "hierarchical impl did not lower to reduce-scatter"
    )
    exact = arr.sum(axis=0)
    scale = max(np.abs(exact).max(), 1e-6)
    tol = 0.02 if dtype == "bfloat16" else 1e-5
    for r in range(world):
        err = np.abs(out[r] - exact).max() / scale
        assert err < tol, (world, dtype, err)


@pytest.mark.parametrize("world", _WORLDS)
@pytest.mark.parametrize("op", ["ReduceScatter", "AllGather"])
def test_rs_ag_hlo_pin_bit_identical(world, op):
    mesh = _mesh(world)
    rng = np.random.RandomState(2)
    if op == "ReduceScatter":
        arr = rng.randn(world, world, 64).astype(np.float32)
        op_fn = spmd(lambda x: m4t.reduce_scatter(x), mesh=mesh)
    else:
        arr = rng.randn(world, 64).astype(np.float32)
        op_fn = spmd(lambda x: m4t.allgather(x), mesh=mesh)
    baseline = np.asarray(op_fn(jnp.asarray(arr)))
    dispatch.set_pins(f"{op}:hlo")
    np.testing.assert_array_equal(
        np.asarray(op_fn(jnp.asarray(arr))), baseline
    )


def test_infeasible_pin_falls_back_to_default():
    """A pinned impl that cannot run at the emission site (here: the
    ring on a 2-rank comm that does not span the 8-device mesh, and
    quantized on an int payload) silently degrades to today's
    behavior instead of mis-lowering."""
    arr = np.arange(2 * 64, dtype=np.float32).reshape(2, 64)
    baseline = _run_allreduce(2, arr, "float32")
    dispatch.set_pins("AllReduce:pallas_ring")
    np.testing.assert_array_equal(
        _run_allreduce(2, arr, "float32"), baseline
    )
    dispatch.set_pins("AllReduce:quantized")
    iarr = np.arange(N * 16, dtype=np.int32).reshape(N, 16)
    mesh = _mesh(N)
    fn = spmd(lambda x: m4t.allreduce(x), mesh=mesh)
    out = np.asarray(fn(jnp.asarray(iarr)))
    np.testing.assert_array_equal(out[0], iarr.sum(axis=0))


# ---------------------------------------------------------------------
# armed plan routing (in-process)
# ---------------------------------------------------------------------


def test_armed_plan_routes_by_key_and_logs_decisions():
    key = planmod.plan_key("AllReduce", nbytes=512 * 4, dtype="float32",
                           world=N, axes=("ranks",), platform="cpu")
    other = planmod.plan_key("AllReduce", nbytes=1 << 20, dtype="float32",
                             world=N, axes=("ranks",), platform="cpu")
    planobj = planmod.Plan(platform="cpu", entries={
        key: planmod.PlanEntry("quantized", source="measured"),
        other: planmod.PlanEntry("hlo"),
    })
    dispatch.arm(planobj)
    text = _lowered(N, 512, jnp.float32)
    assert "all_reduce" not in text and "collective_permute" in text
    # a payload in a *different* bucket has no entry: default (hlo)
    text2 = _lowered(N, 4096, jnp.float32)
    assert "all_reduce" in text2
    log = dispatch.decision_log()
    assert log[key] == "quantized"
    ann = dispatch.bench_annotation()
    assert ann["id"] == planobj.plan_id
    assert "quantized" in ann["impls"]["AllReduce"]


def test_plan_for_wrong_platform_disarms(capsys):
    planobj = planmod.Plan(platform="tpu:v5e", entries={})
    dispatch.arm(planobj)
    _lowered(N, 64, jnp.float32)
    assert dispatch.active is None, "wrong-platform plan must disarm"
    assert "disarming plan" in capsys.readouterr().err


# ---------------------------------------------------------------------
# plan key <-> fingerprint drift pins (satellite 3)
# ---------------------------------------------------------------------


def test_plan_key_joins_runtime_static_and_recorder_layers():
    """The same collective seen by (a) the metrics registry, (b) the
    flight recorder, and (c) the static linter produces one plan key,
    pinned literally."""
    from mpi4jax_tpu.analysis import lint

    obs.enable()
    obs.flight_recorder.reset()

    def program(x):
        return m4t.allreduce(x + 1)

    spmd(program, mesh=_mesh(N))(jnp.ones((N, 4096), jnp.float32))
    emission = [r for r in obs.snapshot()["emissions"]
                if r["op"] == "AllReduce"][-1]
    recorded = [r for r in obs.flight_recorder.snapshot()
                if r["op"] == "AllReduce"][-1]
    report = lint(program, (jax.ShapeDtypeStruct((4096,), jnp.float32),),
                  axis_env={"ranks": N})
    (site,) = [s for s in report.sites if s.op == "AllReduce"]
    keys = {
        planmod.key_from_record(emission, "cpu"),
        planmod.key_from_record(recorded, "cpu"),
        planmod.key_from_record(site.to_json(), "cpu"),
    }
    assert keys == {"AllReduce|b15|float32|w8|ranks|cpu"}, keys
    # and the recorder fingerprint itself is unchanged by the planner
    from mpi4jax_tpu.observability.recorder import fingerprint

    assert fingerprint(recorded) == site.fingerprint


# ---------------------------------------------------------------------
# static layer: armed cost report carries the impl tag
# ---------------------------------------------------------------------


def test_static_cost_report_reflects_armed_plan():
    from mpi4jax_tpu.analysis.schedule import cost_report, trace_schedule

    def program(x):
        return m4t.allreduce(x)

    args = (jax.ShapeDtypeStruct((4096,), jnp.float32),)
    sched = trace_schedule(program, args, axis_env={"ranks": N})
    plain = cost_report(sched)
    assert all("impl" not in g for g in plain["top"])

    dispatch.set_pins("AllReduce:quantized")
    armed = cost_report(sched)
    (top,) = [g for g in armed["top"] if g["op"] == "AllReduce"]
    assert top["impl"] == "quantized"
    # quantized moves fewer wire bytes than the exact ring
    plain_top = [g for g in plain["top"] if g["op"] == "AllReduce"][0]
    assert top["wire_bytes"] < plain_top["wire_bytes"]
