"""Tensor-parallel mat-vec with transpose ladder, mirroring the
reference's ``tests/collective_ops/test_allreduce_matvec.py:12-239``:
a column-partitioned distributed operator ``A @ x = allreduce(A_loc @
x_loc)`` whose ``linear_transpose`` must automatically yield the
row-partitioned transposed operator, verified against a dense ground
truth computed redundantly on every rank, through three levels of
transposition."""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4t

N = 8
DIM = N * 3  # global matrix dimension, divisible by world size


def make_global(seed=42):
    rng = np.random.RandomState(seed)
    A = rng.rand(DIM, DIM).astype(np.float32)
    x = rng.rand(DIM).astype(np.float32)
    return A, x


def partition_cols(A):
    """Column partition: rank r owns A[:, r*k:(r+1)*k] (reference
    test_allreduce_matvec.py:41-60)."""
    k = DIM // N
    return np.stack([A[:, r * k : (r + 1) * k] for r in range(N)])


def partition_rows(x):
    k = DIM // N
    return np.stack([x[r * k : (r + 1) * k] for r in range(N)])


def matvec_local(A_loc, x_loc):
    return m4t.allreduce(A_loc @ x_loc, op=m4t.SUM)


def test_distributed_matvec(run_spmd):
    A, x = make_global()
    out = run_spmd(matvec_local, partition_cols(A), partition_rows(x))
    expected = A @ x
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4)


def test_matvec_transpose(run_spmd):
    """transpose(matvec) is the row-partitioned transposed operator:
    feeding it the full-size cotangent must give each rank its slice
    of A.T @ y (reference test_allreduce_matvec.py:122-150)."""
    A, x = make_global()
    A_cols = partition_cols(A)
    y = np.arange(DIM, dtype=np.float32)

    def f(A_loc, x_loc):
        mv = lambda v: matvec_local(A_loc, v)
        (ct,) = jax.linear_transpose(mv, x_loc)(jnp.asarray(y))
        return ct

    out = run_spmd(f, A_cols, partition_rows(x))
    expected = A.T @ y
    k = DIM // N
    for r in range(N):
        np.testing.assert_allclose(out[r], expected[r * k : (r + 1) * k], rtol=1e-4)


def test_matvec_double_transpose(run_spmd):
    """transpose^2 recovers the forward operator
    (reference test_allreduce_matvec.py:153-179)."""
    A, x = make_global()

    def f(A_loc, x_loc):
        mv = lambda v: matvec_local(A_loc, v)
        mvt = lambda y: jax.linear_transpose(mv, x_loc)(y)[0]
        mvtt = lambda v: jax.linear_transpose(mvt, jnp.zeros(DIM, jnp.float32))(v)[0]
        return mvtt(x_loc)

    out = run_spmd(f, partition_cols(A), partition_rows(x))
    expected = A @ x
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4)


def test_matvec_triple_transpose(run_spmd):
    """Three transposes equal one (reference
    test_allreduce_matvec.py:182-239)."""
    A, x = make_global()
    y = np.arange(DIM, dtype=np.float32)

    def f(A_loc, x_loc):
        mv = lambda v: matvec_local(A_loc, v)
        mvt = lambda w: jax.linear_transpose(mv, x_loc)(w)[0]
        mvtt = lambda v: jax.linear_transpose(mvt, jnp.zeros(DIM, jnp.float32))(v)[0]
        mvttt = lambda w: jax.linear_transpose(mvtt, x_loc)(w)[0]
        return mvttt(jnp.asarray(y))

    out = run_spmd(f, partition_cols(A), partition_rows(x))
    expected = A.T @ y
    k = DIM // N
    for r in range(N):
        np.testing.assert_allclose(out[r], expected[r * k : (r + 1) * k], rtol=1e-4)
