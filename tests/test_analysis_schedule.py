"""Per-rank schedule enumeration (analysis/schedule.py): partial
evaluation of rank-dependent control flow, concrete p2p edges, the
M4T103 precision fix, M4T203 redundancy detection, fingerprint drift
pins, and the static cost report."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import mpi4jax_tpu as m4t
from mpi4jax_tpu.analysis import lint, trace_schedule
from mpi4jax_tpu.analysis.linter import iter_module_targets
from mpi4jax_tpu.analysis.schedule import cost_report, event_cost
from mpi4jax_tpu.observability import costmodel
from mpi4jax_tpu.observability.recorder import fingerprint as rt_fingerprint

N = 4
X = jax.ShapeDtypeStruct((8,), jnp.float32)
RING_DEST = [(r + 1) % N for r in range(N)]
RING_SRC = [(r - 1) % N for r in range(N)]


def sched_of(fn, args=(X,), env=None):
    return trace_schedule(fn, args, axis_env=env or {"ranks": N})


# -- basic enumeration ------------------------------------------------


def test_flat_program_same_schedule_every_rank():
    def step(x):
        return m4t.allgather(m4t.allreduce(x))

    s = sched_of(step)
    assert s.provable and s.world == N
    assert sorted(s.events) == list(range(N))
    for rank in range(N):
        assert [e.op for e in s.events[rank]] == ["AllReduce", "AllGather"]
        assert all(e.group == tuple(range(N)) for e in s.events[rank])


def test_rank_divergent_cond_resolved_per_rank():
    def step(x):
        r = lax.axis_index("ranks")
        y = lax.cond(r == 0, lambda v: m4t.allreduce(v), lambda v: v, x)
        return m4t.allgather(y)

    s = sched_of(step)
    assert s.provable
    assert [e.op for e in s.events[0]] == ["AllReduce", "AllGather"]
    for rank in range(1, N):
        assert [e.op for e in s.events[rank]] == ["AllGather"]


def test_ring_edges_are_concrete_global_ranks():
    def step(x):
        m4t.send(x, RING_DEST, tag=1)
        return m4t.recv(x, RING_SRC, tag=1)

    s = sched_of(step)
    for rank in range(N):
        (e,) = s.events[rank]
        assert e.edges == tuple((k, (k + 1) % N) for k in range(N))
        assert e.sends == ((rank + 1) % N,)
        assert e.recvs == ((rank - 1) % N,)
        assert e.group == tuple(range(N))


def test_scan_unrolls_static_length():
    def step(x):
        def body(c, _):
            return m4t.allreduce(c), None

        y, _ = lax.scan(body, x, None, length=3)
        return y

    s = sched_of(step)
    for rank in range(N):
        assert [e.op for e in s.events[rank]] == ["AllReduce"] * 3


def test_uniform_while_counts_one_iteration_with_note():
    # cg_solver-shaped: the trip count depends on an allreduce output,
    # unknown but provably rank-uniform -> one representative
    # iteration, flagged in the notes
    def step(x):
        rs0 = m4t.allreduce(jnp.vdot(x, x))

        def cond(state):
            _, rs = state
            return rs > 1e-6

        def body(state):
            v, _ = state
            v = v * 0.5
            return v, m4t.allreduce(jnp.vdot(v, v))

        v, _ = lax.while_loop(cond, body, (x, rs0))
        return v

    s = sched_of(step)
    assert s.provable
    for rank in range(N):
        assert [e.op for e in s.events[rank]] == ["AllReduce", "AllReduce"]
    assert any("rank-uniform" in n for n in s.notes)


def test_concrete_rank_dependent_while_iterates_per_rank():
    # trip count = rank: schedules genuinely differ per rank — the
    # enumeration must produce them (the simulator then proves the
    # deadlock, which M4T101 could only suspect)
    def step(x):
        r = lax.axis_index("ranks")

        def cond(state):
            v, it = state
            return it < r

        def body(state):
            v, it = state
            return m4t.allreduce(v), it + 1

        v, _ = lax.while_loop(cond, body, (x, jnp.asarray(0, jnp.int32)))
        return v

    s = sched_of(step)
    assert s.provable
    for rank in range(N):
        assert len(s.events[rank]) == rank


def test_divergent_data_cond_with_differing_branches_unprovable():
    def step(x):
        return lax.cond(
            x.sum() > 0,
            lambda v: m4t.allreduce(v),
            lambda v: m4t.allgather(v)[0] * 1.0,
            x,
        )

    s = sched_of(step)
    assert not s.provable
    assert "differing collective schedules" in s.unprovable


def test_uniform_data_cond_with_identical_branches_provable():
    def step(x):
        s0 = m4t.allreduce(x.sum())
        return lax.cond(
            s0 > 0,
            lambda v: m4t.allreduce(v),
            lambda v: m4t.allreduce(v * 2),
            x,
        )

    s = sched_of(step)
    assert s.provable
    for rank in range(N):
        assert [e.op for e in s.events[rank]] == ["AllReduce", "AllReduce"]


def test_multi_axis_groups():
    # dp collective groups ranks sharing the tp coordinate and vice
    # versa (env order row-major: dp is the slow axis)
    def step(x):
        y = m4t.allreduce(x, comm=m4t.Comm("dp"))
        return m4t.allreduce(y, comm=m4t.Comm("tp"))

    s = sched_of(step, env={"dp": 2, "tp": 2})
    assert s.world == 4
    dp_ev, tp_ev = s.events[0]
    assert dp_ev.group == (0, 2)  # ranks with tp-coord 0
    assert tp_ev.group == (0, 1)  # ranks with dp-coord 0
    dp_ev3, tp_ev3 = s.events[3]
    assert dp_ev3.group == (1, 3)
    assert tp_ev3.group == (2, 3)


# -- fingerprint drift pins (extends the PR 3 pin) --------------------


def test_schedule_fingerprint_byte_identical_to_site_and_recorder():
    def step(x):
        return m4t.allreduce(x)

    rep = lint(step, (X,), axis_env={"ranks": N})
    s = sched_of(step)
    (site,) = rep.sites
    (event,) = s.events[0]
    runtime_record = {
        "op": "AllReduce",
        "shape": [8],
        "bytes": 32,
        "dtype": "float32",
        "axes": ["ranks"],
    }
    pinned = "AllReduce[8:float32]@ranks"
    assert event.fingerprint == pinned
    assert site.fingerprint == pinned
    assert rt_fingerprint(runtime_record) == pinned


def test_p2p_schedule_fingerprint_matches_site():
    def step(x):
        return m4t.sendrecv(x, x, RING_SRC, RING_DEST)

    rep = lint(step, (X,), axis_env={"ranks": N})
    s = sched_of(step)
    assert s.events[0][0].fingerprint == rep.sites[0].fingerprint
    assert s.events[0][0].fingerprint == (
        "CollectivePermute[8:float32]@ranks"
    )


# -- M4T103 precision (ring / shift / self-edge regressions) ----------


def test_m4t103_full_ring_clean():
    def ok(x):
        return m4t.sendrecv(x, x, RING_SRC, RING_DEST)

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert rep.findings == []


def test_m4t103_open_shift_with_proc_null_clean():
    # non-periodic chain shift: boundary ranks have no partner
    src = tuple((r - 1) if r >= 1 else m4t.PROC_NULL for r in range(N))
    dst = tuple((r + 1) if r + 1 < N else m4t.PROC_NULL for r in range(N))

    def ok(x):
        return m4t.sendrecv(x, x, src, dst)

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert rep.findings == []


def test_m4t103_degenerate_all_self_edges_flagged():
    table = [(r + N) % N for r in range(N)]

    def bad(x):
        return m4t.sendrecv(x, x, table, table)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert [f.code for f in rep.findings] == ["M4T103"]
    assert "entirely of self-edges" in rep.findings[0].message


def test_m4t103_single_deliberate_self_edge_not_flagged():
    # the precision fix: one rank keeping its own value while the
    # others rotate is legal CollectivePermute routing and used to
    # false-positive as "degenerate shift arithmetic"
    dest = [1, 2, 0, 3]  # ranks 0-2 rotate, rank 3 keeps its value
    src = [2, 0, 1, 3]

    def ok(x):
        return m4t.sendrecv(x, x, src, dest)

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert rep.findings == []
    # and the schedule shows the per-rank pairing concretely
    s = sched_of(ok)
    assert s.events[3][0].sends == (3,)
    assert s.events[3][0].recvs == (3,)
    from mpi4jax_tpu.analysis.simulate import simulate_events

    ok_sim, _, findings = simulate_events(s.events)
    assert ok_sim and findings == []


# -- M4T203: redundant collective -------------------------------------


def test_m4t203_double_allreduce_detected():
    def bad(x):
        return m4t.allreduce(m4t.allreduce(x))

    s = sched_of(bad)
    assert len(s.redundant) == 1
    pair = s.redundant[0]
    assert pair.fingerprint == "AllReduce[8:float32]@ranks"
    assert pair.reduce_op == "SUM"


def test_m4t203_not_fired_when_value_modified_between():
    def ok(x):
        return m4t.allreduce(m4t.allreduce(x) * 2.0)

    s = sched_of(ok)
    assert s.redundant == []


def test_m4t203_ring_rotation_not_redundant():
    # repeated CollectivePermute of the same buffer is a ring — each
    # hop moves data one step further (the ring-attention regression)
    def ok(x):
        def body(c, _):
            c = m4t.sendrecv(c, c, RING_SRC, RING_DEST)
            return c, None

        y, _ = lax.scan(body, x, None, length=3)
        return y

    s = sched_of(ok)
    assert s.redundant == []


# -- static cost report ------------------------------------------------


def test_event_cost_matches_costmodel():
    def step(x):
        return m4t.allgather(m4t.allreduce(x))

    s = sched_of(step)
    ar, ag = s.events[0]
    # the PR 4 golden numbers: 32B payload, n=4 ring algorithms
    assert event_cost(ar) == costmodel.cost(
        "AllReduce", nbytes=32, world=N, dtype="float32"
    )
    assert event_cost(ar)["wire_bytes"] == 48  # 2*(n-1)/n * 32
    assert event_cost(ag)["wire_bytes"] == 96  # (n-1) * 32


@pytest.mark.perf
def test_shallow_water_cost_matches_pr4_golden_table():
    """Acceptance pin: ``lint --cost`` predicted wire bytes for the
    shallow_water target equal the analytic cost model's numbers
    (PR 4 golden table: CollectivePermute wire = payload bytes)."""
    mod = importlib.import_module("mpi4jax_tpu.models.shallow_water")
    ((_, target),) = list(iter_module_targets(mod, world=8))
    s = trace_schedule(target.fn, target.args, axis_env=target.axis_env)
    assert s.provable and s.world == 8
    rep = cost_report(s)
    # every rank: 20 halo permutes, f32 payloads 1x6/2x6/3x6/4x6 on a
    # (2, 4) grid of the 16x8 domain -> 1152 wire bytes, 20 steps
    for rank in range(8):
        assert rep["per_rank"][str(rank)]["wire_bytes"] == 1152
        assert rep["per_rank"][str(rank)]["steps"] == 20
        assert rep["per_rank"][str(rank)]["n_events"] == 20
    # byte-identical to summing the runtime cost model over the events
    for rank, events in s.events.items():
        assert rep["per_rank"][str(rank)]["wire_bytes"] == sum(
            costmodel.cost(
                e.op, nbytes=e.nbytes, world=e.world, dtype=e.dtype
            )["wire_bytes"]
            for e in events
        )
    assert rep["top"], "dominant-collectives table must not be empty"
    assert rep["program"]["expected_s"] > 0


def test_cost_report_alpha_beta_time():
    def step(x):
        return m4t.allreduce(x)

    s = sched_of(step)
    rep = cost_report(s, gbps=1.0)  # 1 GB/s, alpha default 1us/step
    c = costmodel.cost("AllReduce", nbytes=32, world=N, dtype="float32")
    expected = c["steps"] * 1e-6 + c["wire_bytes"] / 1e9
    assert np.isclose(rep["program"]["expected_s"], expected)


# -- world-parametrized module targets --------------------------------


def test_iter_module_targets_world_reinstantiates():
    mod = importlib.import_module("mpi4jax_tpu.models.mlp")
    ((_, t2),) = list(iter_module_targets(mod, world=2))
    assert int(np.prod(list(t2.axis_env.values()))) == 2
    ((_, t8),) = list(iter_module_targets(mod, world=8))
    assert int(np.prod(list(t8.axis_env.values()))) == 8


def test_iter_module_targets_skips_unscalable_mismatched_world():
    import types

    from mpi4jax_tpu.analysis import LintTarget

    def fixed_thunk():
        return LintTarget(fn=lambda x: x, args=(X,), axis_env={"ranks": 4})

    mod = types.SimpleNamespace(
        __name__="fake", M4T_LINT_TARGETS={"fixed": fixed_thunk}
    )
    assert list(iter_module_targets(mod, world=8)) == []
    assert len(list(iter_module_targets(mod, world=4))) == 1
    assert len(list(iter_module_targets(mod))) == 1
