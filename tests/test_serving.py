"""Serving plane (``mpi4jax_tpu/serving/``): job spool, fair
scheduler, queue-draining supervisor, queue-level metrics.

Covers the ISSUE-10 acceptance surface:

- job-spec validation: every malformed field class gets a clear
  ``JobSpecError`` naming the field;
- spool protocol: atomic submit (tmp+rename), atomic claim (the
  rename race has exactly one winner), finish accounting, bounded
  backpressure — submits past capacity are *explicitly rejected*
  (``queue_full``) with a load-shed audit record, drain closes
  admission while the queue still empties;
- scheduler: FIFO within a tenant, round-robin across tenants (a
  chatty tenant cannot starve the others), deterministic;
- server (stub runner — device-free): per-job fault domains (one
  job's failure never takes the server down), per-job RetryPolicy
  budgets, admission verify gate rejections, elastic capacity shrink
  on preemption with a *real* resharded m4t-ckpt/2 checkpoint, and
  audit accounting for every submitted job id;
- queue-level OpenMetrics export: depth/capacity gauges, outcome and
  per-reason rejection counters, the ``# EOF`` contract;
- the doctor's serving timeline narration;
- e2e (real spawned worlds, no collectives — device-free): trivial
  jobs complete through ``launch.spawn_world``, deadlines grace-kill
  wedged jobs, CLI submit/status/drain round-trip, ``--selftest``;
- chaos e2e (slow, ``-m 'chaos and serving'``): 4 queued jobs, one
  preempted mid-queue under ``serve --elastic`` — resident job
  reshards and completes at the shrunk world, queued jobs drain
  there too, and the audit accounts for every job id.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from mpi4jax_tpu.observability import doctor
from mpi4jax_tpu.resilience import ckpt as _ckpt
from mpi4jax_tpu.resilience.reshard import LeafSpec
from mpi4jax_tpu.serving import (
    FairScheduler,
    JobSpecError,
    Server,
    Spool,
    parse_job,
)
from mpi4jax_tpu.serving import export as sexport
from mpi4jax_tpu.serving.spool import DEFAULT_CAPACITY

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


# ---------------------------------------------------------------------
# job-spec validation
# ---------------------------------------------------------------------


@pytest.mark.parametrize("bad, needle", [
    ("{not json", "not valid JSON"),
    ("[1, 2]", "JSON object"),
    ('{"cmd": ["x"], "gpus": 4}', "unknown field"),
    ('{"cmd": ["x"], "module": "m"}', "exactly one"),
    ('{"nproc": 2}', "exactly one"),
    ('{"cmd": [], "nproc": 1}', "cmd"),
    ('{"cmd": [1]}', "cmd"),
    ('{"module": ""}', "module"),
    ('{"cmd": ["x"], "nproc": 0}', "nproc"),
    ('{"cmd": ["x"], "nproc": true}', "nproc"),
    ('{"cmd": ["x"], "timeout_s": -5}', "timeout_s"),
    ('{"cmd": ["x"], "retries": -1}', "retries"),
    ('{"cmd": ["x"], "backoff_s": -1}', "backoff_s"),
    ('{"cmd": ["x"], "verify": "yes"}', "verify"),
    ('{"cmd": ["x"], "tenant": "has space"}', "tenant"),
    ('{"cmd": ["x"], "id": "-leading-dash"}', "id"),
    ('{"cmd": ["x"], "env": {"A": 1}}', "env"),
    ('{"cmd": ["x"], "resume_dir": 7}', "resume_dir"),
    ('{"cmd": ["x"], "schema": "m4t-job/9"}', "schema"),
    ('{"cmd": ["x"], "fault_plan": {"faults": []}}', "fault_plan"),
])
def test_job_spec_rejects_bad_fields(bad, needle):
    with pytest.raises(JobSpecError) as ei:
        parse_job(bad)
    assert needle in str(ei.value), (bad, ei.value)


def test_job_spec_defaults_and_roundtrip():
    spec = parse_job({"cmd": ["train.py", "--lr", "0.1"]})
    assert spec.tenant == "default" and spec.nproc == 1
    assert spec.retries == 0 and spec.timeout_s == 0.0
    assert spec.target == "train.py"
    again = parse_job(spec.to_json())
    assert again.to_json() == spec.to_json()
    mod = parse_job({"module": "pkg.mod", "nproc": 4, "tenant": "t1",
                     "retries": 2, "backoff_s": 0.1, "verify": True,
                     "env": {"A": "b"}})
    assert mod.target == "pkg.mod" and mod.env == {"A": "b"}
    assert parse_job(mod.to_json()).to_json() == mod.to_json()


# ---------------------------------------------------------------------
# spool protocol
# ---------------------------------------------------------------------


def test_spool_submit_claim_finish_accounting(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.capacity == DEFAULT_CAPACITY
    r = spool.submit({"id": "a1", "tenant": "a", "cmd": ["-c", "pass"]})
    # the response carries the trace id minted at submit (PR 12)
    assert r["job"] == "a1" and r["status"] == "queued"
    assert r["trace"]
    (spec,) = spool.pending()
    assert spec.id == "a1" and spec.submitted_t is not None
    # atomic claim: exactly one winner for the rename race
    assert spool.claim(spec) is not None
    assert spool.claim(spec) is None
    assert spool.pending() == [] and len(spool.running()) == 1
    spool.finish(spec, "completed", world=1, attempts=1,
                 queue_wait_s=0.0, run_s=0.1)
    assert spool.running() == []
    (done,) = spool.done()
    assert done["id"] == "a1" and done["outcome"] == "completed"
    # duplicate ids are rejected even after the job finished
    dup = spool.submit({"id": "a1", "cmd": ["-c", "pass"]})
    assert dup["status"] == "rejected" and dup["reason"] == "duplicate_id"


def test_spool_backpressure_is_bounded_and_audited(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(2)
    assert spool.capacity == 2
    assert spool.submit({"id": "q0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    assert spool.submit({"id": "q1", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    shed = spool.submit({"id": "q2", "tenant": "t9",
                         "cmd": ["-c", "pass"]})
    assert shed == {
        "job": "q2", "status": "rejected", "reason": "queue_full",
        "depth": 2, "capacity": 2,
    }
    assert spool.depth() == 2  # never grew past the cap
    # the load-shed audit record names who was shed and why
    recs = [r for r in spool.audit_records()
            if r["event"] == "rejected"]
    assert len(recs) == 1
    assert recs[0]["job"] == "q2" and recs[0]["tenant"] == "t9"
    assert recs[0]["reason"] == "queue_full"
    assert recs[0]["depth"] == 2 and recs[0]["capacity"] == 2


def test_spool_drain_closes_admission_but_queue_drains(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "d0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    spool.request_drain("test")
    assert spool.draining()
    late = spool.submit({"id": "d1", "cmd": ["-c", "pass"]})
    assert late["status"] == "rejected" and late["reason"] == "draining"
    # the queued job is still claimable — drain is not a drop
    (spec,) = spool.pending()
    assert spool.claim(spec) is not None


def test_spool_skips_garbage_entries(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.submit({"id": "ok", "cmd": ["-c", "pass"]})
    with open(os.path.join(spool.root, "pending",
                           f"{0:020d}-torn.json"), "w") as f:
        f.write('{"cmd": [')  # torn by a killed submitter
    specs = spool.pending()
    assert [s.id for s in specs] == ["ok"]


# ---------------------------------------------------------------------
# fair scheduler
# ---------------------------------------------------------------------


def _pending(entries):
    out = []
    for i, (jid, tenant) in enumerate(entries):
        spec = parse_job({"id": jid, "tenant": tenant,
                          "cmd": ["-c", "pass"]})
        spec.entry = f"{i:020d}-{jid}.json"
        out.append(spec)
    return out


def test_scheduler_is_fifo_for_one_tenant():
    sched = FairScheduler()
    pending = _pending([("j0", "a"), ("j1", "a"), ("j2", "a")])
    order = []
    while pending:
        s = sched.pick(pending)
        order.append(s.id)
        pending = [p for p in pending if p.id != s.id]
    assert order == ["j0", "j1", "j2"]
    assert sched.pick([]) is None


def test_scheduler_round_robin_prevents_starvation():
    # tenant a floods the queue; b and c each submit one job later —
    # they are served after a's *first* job, not after a's backlog
    sched = FairScheduler()
    pending = _pending([
        ("a0", "a"), ("a1", "a"), ("a2", "a"), ("a3", "a"),
        ("b0", "b"), ("c0", "c"),
    ])
    order = []
    while pending:
        s = sched.pick(pending)
        order.append(s.id)
        pending = [p for p in pending if p.id != s.id]
    assert order == ["a0", "b0", "c0", "a1", "a2", "a3"], order


def test_scheduler_is_deterministic():
    runs = []
    for _ in range(2):
        sched = FairScheduler()
        pending = _pending([
            ("x0", "x"), ("y0", "y"), ("x1", "x"), ("z0", "z"),
            ("y1", "y"),
        ])
        order = []
        while pending:
            s = sched.pick(pending)
            order.append(s.id)
            pending = [p for p in pending if p.id != s.id]
        runs.append(order)
    assert runs[0] == runs[1]


# ---------------------------------------------------------------------
# server over a stub runner (device-free)
# ---------------------------------------------------------------------


def _serve(spool, runner, **kw):
    kw.setdefault("nproc", 2)
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("log", lambda msg: None)
    server = Server(spool, runner=runner, **kw)
    rc = server.serve()
    return server, rc


def test_server_per_job_fault_domains_and_budgets(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    for obj in (
        {"id": "ok", "cmd": ["-c", "pass"]},
        {"id": "flaky", "cmd": ["-c", "pass"], "retries": 3,
         "backoff_s": 0.0},
        {"id": "doomed", "cmd": ["-c", "pass"], "retries": 1,
         "backoff_s": 0.0},
    ):
        assert spool.submit(obj)["status"] == "queued"
    calls = []

    def runner(spec, world, events_dir, attempt, resume_step):
        calls.append((spec.id, attempt))
        if spec.id == "flaky":
            return (0, []) if attempt == 2 else (7, [])
        return (1, []) if spec.id == "doomed" else (0, [])

    server, rc = _serve(spool, runner, max_jobs=3)
    assert rc == 0
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {
        "ok": "completed", "flaky": "completed", "doomed": "failed",
    }
    # each job consumed exactly its own retry budget
    assert [a for (j, a) in calls if j == "flaky"] == [0, 1, 2]
    assert [a for (j, a) in calls if j == "doomed"] == [0, 1]
    done = {r["id"]: r for r in spool.done()}
    assert done["doomed"]["exit_code"] == 1
    assert done["flaky"]["attempts"] == 3
    # the audit accounts for every submitted id
    ended = {
        r["job"] for r in spool.audit_records()
        if r["event"] in ("completed", "failed", "rejected")
    }
    assert ended == {"ok", "flaky", "doomed"}


def test_server_mismatch_fails_fast_within_the_job(tmp_path):
    """A deterministic verdict (MISMATCH, per the doctor) must not
    burn the job's retry budget — and must not take the server down."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({
        "id": "forked", "cmd": ["-c", "pass"], "retries": 5,
        "backoff_s": 0.0,
    })["status"] == "queued"
    assert spool.submit({"id": "after", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    calls = []

    def runner(spec, world, events_dir, attempt, resume_step):
        calls.append(spec.id)
        if spec.id == "forked":
            # leave a 2-rank mismatch trail the doctor will classify
            # as deterministic
            for rank, op in ((0, "AllReduce"), (1, "Bcast")):
                path = os.path.join(
                    events_dir, f"events-rank{rank}.jsonl"
                )
                with open(path, "w") as f:
                    f.write(json.dumps({
                        "kind": "emission", "rank": rank, "seq": 1,
                        "op": op, "bytes": 64, "dtype": "float32",
                        "shape": [16], "axes": [], "world": 2,
                        "cid": f"c{rank}", "t": 100.0,
                    }) + "\n")
            return 1, []
        return 0, []

    server, rc = _serve(spool, runner, max_jobs=2)
    assert rc == 0
    assert calls.count("forked") == 1  # deterministic: one attempt
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {"forked": "failed", "after": "completed"}
    rec = {r["id"]: r for r in spool.done()}["forked"]
    assert rec["klass"] == "deterministic"
    assert "mismatch" in rec["reason"]


def test_server_verify_gate_rejects_before_the_mesh(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({
        "id": "unprovable", "cmd": ["-c", "pass"], "verify": True,
    })["status"] == "queued"
    ran = []

    def runner(spec, world, events_dir, attempt, resume_step):
        ran.append(spec.id)
        return 0, []

    server, rc = _serve(
        spool, runner, max_jobs=1,
        verify_fn=lambda spec, world: False,
    )
    assert rc == 0
    assert ran == []  # never spawned: rejected at admission
    (rec,) = spool.done()
    assert rec["outcome"] == "rejected"
    assert rec["reason"] == "verify_failed"
    recs = [r for r in spool.audit_records()
            if r["event"] == "rejected"]
    assert recs and recs[0]["reason"] == "verify_failed"


def test_server_elastic_shrink_reshards_and_resumes(tmp_path):
    """Preemption under --elastic: capacity shrinks for good, the
    resident job's real m4t-ckpt/2 checkpoint is resharded 2 -> 1,
    the job resumes from the resharded step at the shrunk world, and
    later jobs serve at the smaller world."""
    spool = Spool(str(tmp_path / "sp"))
    ckroot = str(tmp_path / "ck")
    mgr = _ckpt.CheckpointManager(ckroot, keep=2, world=2)
    mgr.save_sharded(
        7, {"w": np.arange(10.0, dtype=np.float64)},
        {"w": LeafSpec(shape=(10,), dtype="float64")},
    )
    for obj in (
        {"id": "resident", "cmd": ["-c", "pass"], "nproc": 2,
         "retries": 2, "backoff_s": 0.0, "resume_dir": ckroot},
        {"id": "queued2", "cmd": ["-c", "pass"], "nproc": 2},
    ):
        assert spool.submit(obj)["status"] == "queued"
    calls = []

    def runner(spec, world, events_dir, attempt, resume_step):
        calls.append((spec.id, world, attempt, resume_step))
        if spec.id == "resident" and attempt == 0:
            return 143, [1]
        return 0, []

    server, rc = _serve(
        spool, runner, max_jobs=2, elastic=True, min_ranks=1,
    )
    assert rc == 0
    assert server.capacity == 1
    assert calls == [
        ("resident", 2, 0, None),
        ("resident", 1, 1, 7),   # resumed from the resharded step
        ("queued2", 1, 0, None),  # the shrink outlived the job
    ], calls
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {
        "resident": "completed", "queued2": "completed",
    }
    # the resharded checkpoint exists at world 1 with provenance
    info = _ckpt.CheckpointManager(ckroot, world=1).latest_valid(
        world=1)
    assert info is not None and info.step == 7
    assert info.manifest["resharded_from"]["world"] == 2
    # the world transition is audited with the reshard source
    (world_rec,) = [r for r in spool.audit_records()
                    if r["event"] == "world"]
    assert world_rec["world"] == 2 and world_rec["next_world"] == 1
    assert world_rec["preempted_ranks"] == [1]
    assert world_rec["resharded_from_step"] == 7
    assert world_rec["resharded_from_world"] == 2


def test_server_below_min_ranks_stops_serving(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({
        "id": "fatal", "cmd": ["-c", "pass"], "nproc": 2,
        "retries": 2, "backoff_s": 0.0,
    })["status"] == "queued"

    def runner(spec, world, events_dir, attempt, resume_step):
        return 143, [0, 1]  # the whole mesh preempted

    server, rc = _serve(
        spool, runner, elastic=True, min_ranks=2, max_jobs=5,
    )
    assert rc == 1  # cannot honestly keep serving
    assert server.capacity == 0
    (rec,) = spool.done()
    assert rec["outcome"] == "failed"
    assert "below --min-ranks" in rec["reason"]


def test_server_internal_error_is_the_jobs_fault_domain(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "boom", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    assert spool.submit({"id": "fine", "cmd": ["-c", "pass"]})[
        "status"] == "queued"

    def runner(spec, world, events_dir, attempt, resume_step):
        if spec.id == "boom":
            raise RuntimeError("runner exploded")
        return 0, []

    server, rc = _serve(spool, runner, max_jobs=2)
    assert rc == 0
    outcomes = {r["id"]: r["outcome"] for r in spool.done()}
    assert outcomes == {"boom": "failed", "fine": "completed"}


# ---------------------------------------------------------------------
# queue-level OpenMetrics export
# ---------------------------------------------------------------------


def test_export_counters_and_contract(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(1)
    assert spool.submit({"id": "m0", "tenant": "t0",
                         "cmd": ["-c", "pass"]})["status"] == "queued"
    assert spool.submit({"id": "m1", "cmd": ["-c", "pass"]})[
        "reason"] == "queue_full"
    server, rc = _serve(
        spool, lambda *a: (0, []), nproc=1, max_jobs=1,
    )
    assert rc == 0
    snap = sexport.serving_snapshot(spool)
    assert snap["depth"] == 0 and snap["capacity"] == 1
    assert snap["counts"]["submitted"] == 1
    assert snap["counts"]["completed"] == 1
    assert snap["rejected"] == {"queue_full": 1}
    assert snap["world"] == 1
    text = sexport.render_serving_metrics(snap)
    assert text.endswith("# EOF\n")
    assert "m4t_serve_queue_depth 0" in text
    assert "m4t_serve_queue_capacity 1" in text
    assert 'm4t_serve_jobs_total{outcome="completed"} 1' in text
    assert 'm4t_serve_rejected_total{reason="queue_full"} 1' in text
    assert 'm4t_serve_job_queue_wait_seconds{job="m0",tenant="t0"}' in text
    # the atomic snapshot file the server refreshes
    path = sexport.write_serving_prom(spool)
    assert os.path.basename(path) == "metrics.prom"
    assert open(path).read().endswith("# EOF\n")


def test_export_served_over_http(tmp_path):
    from urllib.request import urlopen

    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "h0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    server = Server(
        spool, nproc=1, max_jobs=1, poll_s=0.01,
        runner=lambda *a: (0, []), metrics_port=0,
        log=lambda msg: None,
    )
    server._start_metrics()
    try:
        port = server._http.server_port
        body = urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "m4t_serve_queue_depth 1" in body
        assert body.endswith("# EOF\n")
    finally:
        server._stop_metrics()


# ---------------------------------------------------------------------
# doctor narration
# ---------------------------------------------------------------------


def test_doctor_serving_timeline_narrates(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(1)
    spool.submit({"id": "n0", "tenant": "a", "cmd": ["-c", "pass"],
                  "nproc": 2, "retries": 1, "backoff_s": 0.0})
    spool.submit({"id": "n1", "tenant": "b", "cmd": ["-c", "pass"]})

    def runner(spec, world, events_dir, attempt, resume_step):
        if spec.id == "n0" and attempt == 0:
            return 143, [1]
        return 0, []

    _serve(spool, runner, elastic=True, min_ranks=1, max_jobs=1)
    spool.request_drain()
    # from the spool root and from a per-job attempt dir
    for inputs in ([spool.root],
                   [os.path.join(spool.root, "jobs", "n0",
                                 "attempt00")]):
        recs = doctor.load_serving_audit(inputs)
        assert recs, inputs
        text = doctor.format_serving_timeline(recs)
        assert "REJECTED: job n1 — queue_full" in text
        assert "ELASTIC: world 2 -> 1" in text
        assert "rank(s) 1 preempted" in text
        assert "completed: job n0" in text
        assert "drain requested" in text


# ---------------------------------------------------------------------
# e2e: real spawned worlds (no collectives — toolchain-free)
# ---------------------------------------------------------------------


def test_serve_real_worlds_and_deadline_grace_kill(tmp_path):
    """Real ``launch.spawn_world`` jobs: a clean one completes, a
    wedged one is grace-killed at its own deadline (exit 124) without
    holding the queue hostage."""
    spool = Spool(str(tmp_path / "sp"))
    out = str(tmp_path / "proof.txt")
    assert spool.submit({
        "id": "real", "tenant": "a",
        "cmd": ["-c",
                f"open({out!r}, 'w').write('ran')"],
    })["status"] == "queued"
    assert spool.submit({
        "id": "wedged", "tenant": "b",
        "cmd": ["-c", "import time; time.sleep(120)"],
        "timeout_s": 1.5,
    })["status"] == "queued"
    server = Server(spool, nproc=1, max_jobs=2, poll_s=0.05,
                    log=lambda msg: None)
    t0 = time.monotonic()
    rc = server.serve()
    took = time.monotonic() - t0
    assert rc == 0
    assert open(out).read() == "ran"
    outcomes = {r["id"]: r for r in spool.done()}
    assert outcomes["real"]["outcome"] == "completed"
    assert outcomes["wedged"]["outcome"] == "failed"
    assert outcomes["wedged"]["exit_code"] == 124  # watchdog, not 120s
    assert took < 60, took
    assert os.path.exists(os.path.join(spool.root, "metrics.prom"))


def test_cli_selftest():
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.serving", "--selftest"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert res.returncode == 0, res.stderr
    assert "serving selftest ok" in res.stdout


def test_cli_submit_status_drain_round_trip(tmp_path):
    sp = str(tmp_path / "sp")

    def cli(*argv, timeout=120):
        return subprocess.run(
            [sys.executable, "-m", "mpi4jax_tpu.serving", *argv],
            capture_output=True, text=True, cwd=REPO, timeout=timeout,
        )

    r = cli("submit", sp, "--id", "c1", "--tenant", "demo", "--",
            "-c", "pass")
    assert r.returncode == 0, r.stderr
    resp = json.loads(r.stdout)
    assert resp["job"] == "c1" and resp["status"] == "queued"
    assert resp["trace"]
    # duplicate id: explicit rejection, distinct exit code
    r = cli("submit", sp, "--id", "c1", "--", "-c", "pass")
    assert r.returncode == 3, (r.stdout, r.stderr)
    assert json.loads(r.stdout)["reason"] == "duplicate_id"
    # invalid spec: named field, exit 2
    r = cli("submit", sp, "--id", "c2", "-n", "0", "--", "-c", "pass")
    assert r.returncode == 2 and "nproc" in r.stderr
    r = cli("status", sp, "--json")
    status = json.loads(r.stdout)
    assert status["depth"] == 1
    assert status["pending"][0]["job"] == "c1"
    r = cli("drain", sp)
    assert r.returncode == 0
    r = cli("submit", sp, "--id", "c3", "--", "-c", "pass")
    assert r.returncode == 3
    assert json.loads(r.stdout)["reason"] == "draining"
    # serve drains the queued job and exits 0 on the empty queue
    r = cli("serve", sp, "-n", "1", "--poll", "0.05", timeout=240)
    assert r.returncode == 0, r.stderr
    assert "drained" in r.stderr
    r = cli("status", sp, "--json")
    status = json.loads(r.stdout)
    assert status["outcomes"] == {"completed": 1}


# ---------------------------------------------------------------------
# chaos e2e: mid-queue preemption under serve --elastic
# ---------------------------------------------------------------------

# sharded eager train loop (the test_resilience elastic shape): state
# genuinely split over the world, committed every step via the
# two-phase m4t-ckpt/2 protocol, world-size-independent math
_TRAIN_JOB = """
import sys
import numpy as np
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.runtime import shm
from mpi4jax_tpu.resilience import ckpt, reshard, PreemptGuard, resume_step

STEPS = 6
G = 8
rank, size = shm.rank(), shm.size()
guard = PreemptGuard()
mgr = ckpt.CheckpointManager(sys.argv[1], keep=3, world=size)
specs = {"w": reshard.LeafSpec(shape=(G,), dtype="float32")}
lo, hi = reshard.shard_extent(G, size, rank)
w = np.zeros(hi - lo, np.float32)
start = 0
r = resume_step()
if r is not None:
    info = mgr.at_step(r, world=size)
    if info is not None:
        w = ckpt.load_shard(info, rank)["w"]
        start = info.step + 1
        print(f"RESUMED{rank}@{info.step}", file=sys.stderr)
data = np.arange(G, dtype=np.float32)
for step in range(start, STEPS):
    guard.exit_if_preempted()
    part = np.zeros(G, np.float32)
    part[lo:hi] = data[lo:hi] * (step + 1)
    g = np.asarray(m4t.allreduce(jnp.asarray(part)))
    w = w + np.float32(0.1) * g[lo:hi]
    mgr.stage_shard(step, rank, {"w": w}, specs)
    m4t.barrier()
    if rank == 0:
        mgr.commit_sharded(step, specs)
    m4t.barrier()
"""


@needs_native
@pytest.mark.chaos
@pytest.mark.elastic
@pytest.mark.slow
def test_chaos_mid_queue_preemption_loses_no_job(tmp_path):
    """ISSUE-10 acceptance: 4 queued jobs + 1 shed over capacity; the
    second job is preempted mid-run under ``serve --elastic``. The
    resident job reshards its checkpoint 2 -> 1 and completes at the
    shrunk world, the still-queued jobs drain at the shrunk world,
    and the audit accounts for every submitted job id — nothing is
    silently dropped."""
    script = str(tmp_path / "train_job.py")
    with open(script, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(_TRAIN_JOB))

    spool = Spool(str(tmp_path / "sp"))
    spool.configure(4)
    ckdirs = {}
    for i in range(4):
        jid = f"train{i}"
        ckdirs[jid] = str(tmp_path / f"ck{i}")
        obj = {
            "id": jid, "tenant": "t", "nproc": 2,
            "cmd": [script, ckdirs[jid]],
            "retries": 2, "backoff_s": 0.1,
            "resume_dir": ckdirs[jid],
            "timeout_s": 120.0,
        }
        if i == 1:
            # rank 1's 3rd AllReduce (step 2) gets the preemption
            # notice, on the first attempt only
            obj["fault_plan"] = [{
                "rank": 1, "op": "AllReduce", "nth": 3,
                "action": "preempt", "attempt": 0,
            }]
        assert spool.submit(obj)["status"] == "queued"
    shed = spool.submit({"id": "overflow", "tenant": "t",
                         "cmd": ["-c", "pass"]})
    assert shed["reason"] == "queue_full"

    server = Server(
        spool, nproc=2, elastic=True, min_ranks=1,
        max_jobs=4, poll_s=0.05,
    )
    rc = server.serve()
    assert rc == 0
    assert server.capacity == 1  # the host never came back

    # zero jobs lost: every queued job completed, the shed one is an
    # explicit rejection — all five ids end terminal in the audit
    done = {r["id"]: r for r in spool.done()}
    assert {j: r["outcome"] for j, r in done.items()} == {
        f"train{i}": "completed" for i in range(4)
    }
    terminal = {}
    for r in spool.audit_records():
        if r["event"] in ("completed", "failed", "rejected"):
            terminal[r["job"]] = r["event"]
    assert terminal == {
        "train0": "completed", "train1": "completed",
        "train2": "completed", "train3": "completed",
        "overflow": "rejected",
    }, terminal

    # the resident job was preempted, resharded, resumed smaller
    assert done["train1"]["attempts"] == 2
    assert done["train1"]["world"] == 1  # final attempt's world
    (world_rec,) = [r for r in spool.audit_records()
                    if r["event"] == "world"]
    assert world_rec["world"] == 2 and world_rec["next_world"] == 1
    assert isinstance(world_rec["resharded_from_step"], int)
    info = _ckpt.CheckpointManager(
        ckdirs["train1"], world=1).latest_valid(world=1)
    assert info is not None
    # the still-queued jobs drained at the shrunk world
    assert done["train2"]["world"] == 1
    assert done["train3"]["world"] == 1
    # train0 ran before the shrink, at full capacity
    assert done["train0"]["world"] == 2
    # per-job events dirs exist for the live plane / per-job doctor
    assert os.path.isdir(os.path.join(
        spool.root, "jobs", "train1", "attempt00"))
    assert os.path.isdir(os.path.join(
        spool.root, "jobs", "train1", "attempt01"))
    # the doctor narrates the whole story from the spool root
    text = doctor.format_serving_timeline(
        doctor.load_serving_audit([spool.root]))
    assert "ELASTIC: world 2 -> 1" in text
    assert "REJECTED: job overflow — queue_full" in text
