"""Topology observatory (``mpi4jax_tpu/observability/topology.py``):
measured link maps, per-link attribution, link-localized straggler
diagnosis, and topo-aware planner tuning.

Covers the acceptance surface:

- alpha/beta fit recovery over injectable synthetic link models;
- slow-link detection/localization matrix (which directed edge, how
  slow vs the fleet median) and the link-bound vs rank-bound
  classifier the doctor joins onto confirmed stragglers;
- golden ``m4t-topo/1`` map pin (``tests/data/topo_golden.json``);
- per-link attribution: cid-keyed latency x the cost model's
  directed-edge decomposition -> achieved GB/s per link, exported as
  OpenMetrics gauges and a Perfetto counter track;
- planner consumption: ``tune --topo`` prices candidates over
  per-edge betas and a planted slow link flips the winning impl vs
  the uniform-peak seed (pinned, including ``beta_source``);
- the ``m4t-bwtable/1`` ``sources`` provenance mirror;
- the ``peak_gbps`` bad-``M4T_PEAK_GBPS`` warn-once fallback;
- end-to-end: a real 2-rank ``launch --probe-topology`` run persists
  a validated map with finite fitted betas.

Regen the golden map pin after an intentional schema change::

    python tests/test_topology.py --regen
"""

import json
import math
import os
import subprocess
import sys
import textwrap
import warnings

import pytest

from mpi4jax_tpu.observability import costmodel, doctor, export, topology
from mpi4jax_tpu.planner import autotune, plan as planmod

pytestmark = pytest.mark.topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "topo_golden.json")

#: the fixed synthetic probe the golden file pins: a 4-rank world at
#: 20 GB/s with one 0.5 GB/s directed pair planted across 0<->2
GOLDEN_SPEC = "beta=20,alpha_us=2,0->2=0.5,2->0=0.5"
GOLDEN_WORLD = 4


def golden_topo():
    model = topology.parse_synthetic_spec(GOLDEN_SPEC, world=GOLDEN_WORLD)
    return topology.synthetic_map(model)


def skewed_topo(world=4, slow=((2, 3),), beta=20.0, slow_beta=1.0):
    model = topology.SyntheticLinkModel(
        world, beta_gbps=beta,
        links={e: {"beta_gbps": slow_beta} for e in slow},
    )
    return topology.synthetic_map(model)


# ---------------------------------------------------------------------
# fit + map schema
# ---------------------------------------------------------------------


def test_fit_recovers_planted_alpha_beta():
    model = topology.SyntheticLinkModel(4, alpha_s=3e-6, beta_gbps=18.0)
    alpha, beta = topology.fit_alpha_beta(model.samples()[(0, 1)])
    assert abs(alpha - 3e-6) < 1e-9
    assert abs(beta - 18.0) < 1e-6


def test_fit_degenerate_sweep_degrades_not_crashes():
    # a single payload size cannot separate alpha from beta: the fit
    # collapses to alpha=0 and prices everything as bandwidth
    alpha, beta = topology.fit_alpha_beta([(1 << 20, 1e-3)] * 3)
    assert alpha == 0.0 and beta > 0


def test_map_schema_and_validate():
    topo = golden_topo()
    assert topo["schema"] == topology.SCHEMA == "m4t-topo/1"
    assert topo["world"] == GOLDEN_WORLD
    assert len(topo["edges"]) == GOLDEN_WORLD * (GOLDEN_WORLD - 1)
    for edge in topo["edges"].values():
        assert edge["beta_gbps"] > 0
        assert edge["provenance"] == "synthetic"
    assert topology.validate(topo) is topo


@pytest.mark.parametrize("bad", [
    None,
    {"schema": "nope"},
    {"schema": "m4t-topo/1", "world": 0},
    {"schema": "m4t-topo/1", "world": 2, "edges": {"0->5": {"beta_gbps": 1}}},
    {"schema": "m4t-topo/1", "world": 2, "edges": {"0->1": {"beta_gbps": 0}}},
])
def test_validate_rejects(bad):
    with pytest.raises(ValueError):
        topology.validate(bad)


def test_save_load_find_roundtrip(tmp_path):
    topo = golden_topo()
    run = tmp_path / "run"
    run.mkdir()
    path = topology.save(str(run / topology.MAP_BASENAME), topo)
    assert topology.load(path) == topo
    assert topology.find([str(run)]) == topo
    # a supervised run probes into the run root but the doctor reads
    # per-attempt subdirectories: find() consults the parent too
    attempt = run / "attempt01"
    attempt.mkdir()
    assert topology.find([str(attempt)]) == topo
    assert topology.find([str(tmp_path / "elsewhere")]) is None


def test_topo_golden_pin():
    """The exact ``m4t-topo/1`` document for a fixed synthetic probe
    is a contract (the doctor, the planner, and the CLI all consume
    persisted maps); drift must be deliberate. Regen with
    ``python tests/test_topology.py --regen``."""
    got = golden_topo()
    with open(GOLDEN) as f:
        want = json.load(f)
    assert got == want, (
        "m4t-topo/1 schema drifted from tests/data/topo_golden.json; "
        "if intentional, regen with `python tests/test_topology.py "
        "--regen` and bump topology.SCHEMA if the layout changed"
    )


# ---------------------------------------------------------------------
# slow-link detection / localization matrix
# ---------------------------------------------------------------------


@pytest.mark.parametrize("world,slow", [
    (4, [(2, 3)]),                    # one directed edge
    (4, [(2, 3), (3, 2)]),            # a symmetric pair
    (8, [(0, 4)]),                    # group-crossing edge, larger world
    (8, [(1, 2), (5, 6), (6, 5)]),    # several independent links
])
def test_slow_link_detection_matrix(world, slow):
    topo = skewed_topo(world=world, slow=slow)
    found = topology.slow_links(topo)
    assert {(r["src"], r["dst"]) for r in found} == set(slow)
    for row in found:
        assert row["beta_gbps"] < topology.SLOW_LINK_FACTOR * (
            row["fleet_median_gbps"]
        )
    # slowest-first ordering
    assert [r["beta_gbps"] for r in found] == sorted(
        r["beta_gbps"] for r in found
    )


def test_no_slow_links_on_uniform_fabric():
    topo = skewed_topo(world=4, slow=())
    assert topology.slow_links(topo) == []


def test_classify_rank_link_bound_vs_rank_bound():
    topo = skewed_topo(world=4, slow=[(2, 3)])
    for rank in (2, 3):  # both endpoints of the slow edge
        verdict = topology.classify_rank(topo, rank)
        assert verdict["klass"] == "link-bound"
        assert verdict["slowest_edge"] == "2->3"
        assert verdict["slowest_edge_gbps"] < verdict["fleet_median_gbps"]
    verdict = topology.classify_rank(topo, 0)
    assert verdict["klass"] == "rank-bound"
    assert topology.classify_rank({"schema": "m4t-topo/1", "world": 2,
                                   "edges": {}}, 0) is None


def test_doctor_join_names_the_slow_edge():
    topo = skewed_topo(world=4, slow=[(2, 3)])
    report = {"findings": [
        {"kind": "straggler", "op": "AllReduce", "rank": 2,
         "mean_s": 0.01, "peer_median_s": 0.002, "ratio": 5.0,
         "samples": 8, "min_samples": 5, "peer_samples": {}},
        {"kind": "straggler", "op": "AllReduce", "rank": 0,
         "mean_s": 0.01, "peer_median_s": 0.002, "ratio": 5.0,
         "samples": 8, "min_samples": 5, "peer_samples": {}},
        {"kind": "hang", "rank": 1, "last_seq": 3},
    ]}
    assert doctor.attach_link_classification(report, topo) == 2
    link, rank_b, hang = report["findings"]
    assert link["link_diagnosis"]["klass"] == "link-bound"
    assert rank_b["link_diagnosis"]["klass"] == "rank-bound"
    assert "link_diagnosis" not in hang
    txt = doctor._fmt_finding(link)
    assert "link-bound" in txt and "2->3" in txt
    assert "rank-bound" in doctor._fmt_finding(rank_b)


def test_doctor_cli_auto_detects_map_beside_inputs(tmp_path):
    # straggler logs + a persisted map in the same dir: the CLI joins
    # them without --topo (topology.find auto-detection)
    rundir = tmp_path / "run"
    rundir.mkdir()
    world = 4
    for r in range(world):
        recs = []
        for s in range(1, 7):
            recs.append({
                "kind": "emission", "rank": r, "seq": s, "op": "AllReduce",
                "shape": [8], "dtype": "float32", "axes": ["ranks"],
                "world": world, "bytes": 1 << 20, "cid": f"c{s:04d}",
                "t": 100.0 + s,
            })
            recs.append({
                "kind": "latency", "rank": r, "op": "AllReduce",
                "seconds": 0.05 if r == 2 else 0.001, "cid": f"c{s:04d}",
                "t": 100.0 + s,
            })
        with open(rundir / f"events-rank{r}.jsonl", "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
    topology.save(str(rundir / topology.MAP_BASENAME),
                  skewed_topo(world=world, slow=[(2, 3)]))
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.doctor",
         str(rundir)],
        capture_output=True, text=True, cwd=REPO,
    )
    out = res.stdout + res.stderr
    assert "link-bound" in out, out
    assert "2->3" in out


# ---------------------------------------------------------------------
# edge decomposition + topo-aware cost
# ---------------------------------------------------------------------


def test_ring_edge_phases_conserve_wire_bytes():
    n, b = 8, 1 << 20
    phases = costmodel.edge_phases("AllReduce", nbytes=b, world=n)
    # per-rank outgoing wire of a ring all-reduce: 2(n-1)b/n
    out0 = sum(p["per_edge_bytes"] for p in phases
               for (src, _dst) in p["edges"] if src == 0)
    assert out0 == 2 * (n - 1) * b // n
    assert costmodel.edge_phases("AllReduce", nbytes=b, world=1) == []
    assert costmodel.edge_phases("AllReduce", nbytes=0, world=n) == []


def test_expected_time_topo_slowest_edge_dominates():
    n, b = 4, 1 << 20
    uniform = costmodel.expected_time_topo(
        "AllReduce", nbytes=b, world=n, betas={}, gbps=20.0, alpha=0.0)
    slowed = costmodel.expected_time_topo(
        "AllReduce", nbytes=b, world=n,
        betas={(2, 3): 1.0}, gbps=20.0, alpha=0.0)
    assert slowed > uniform
    # every phase's drain is gated by the planted 1 GB/s edge
    per_hop = (2 * (n - 1) * b / n) / n  # bytes per hop... gated hops
    assert slowed >= per_hop / 1e9  # at least one hop at 1 GB/s
    assert costmodel.expected_time_topo(
        "Send", nbytes=b, world=n, betas={}, gbps=20.0) is None


# ---------------------------------------------------------------------
# per-link attribution
# ---------------------------------------------------------------------


def _attribution_world(world=4, nbytes=1 << 20, seconds=2e-3):
    by_rank = {}
    for r in range(world):
        by_rank[r] = [
            {"kind": "emission", "op": "AllReduce", "bytes": nbytes,
             "dtype": "float32", "world": world, "axes": ["ranks"],
             "seq": 1, "cid": f"c{r}", "rank": r, "t": 1.0},
            {"kind": "latency", "op": "AllReduce", "cid": f"c{r}",
             "seconds": seconds, "rank": r, "t": 1.1},
        ]
    return by_rank


def test_attribute_links_ring_math():
    world, nbytes, seconds = 4, 1 << 20, 2e-3
    topo = skewed_topo(world=world, slow=())
    out = topology.attribute_links(_attribution_world(), topo=topo)
    assert set(out["links"]) == {
        f"{r}->{(r + 1) % world}" for r in range(world)
    }
    row = out["links"]["0->1"]
    expected = (2 * (world - 1) * nbytes / world) / seconds / 1e9
    assert abs(row["gbps_p50"] - expected) < 1e-9
    assert row["samples"] == 1
    assert row["beta_gbps"] == pytest.approx(20.0)
    assert row["vs_probe"] == pytest.approx(expected / row["beta_gbps"])


def test_openmetrics_per_link_gauges():
    out = topology.attribute_links(_attribution_world())
    text = export.render_openmetrics(
        {"ranks": [0, 1, 2, 3], "records": 8}, topo_links=out["links"])
    assert "# TYPE m4t_topo_link_gbps gauge" in text
    assert 'm4t_topo_link_gbps{dst="1",src="0"}' in text
    assert 'm4t_topo_link_probe_gbps' in text
    assert text.rstrip().endswith("# EOF")


def test_trace_gains_links_counter_track():
    from mpi4jax_tpu.observability import trace

    doc = trace.build_trace(_attribution_world())
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"
                and str(e.get("name", "")).startswith("link ")]
    assert counters, doc["traceEvents"][:5]
    names = {e["name"] for e in counters}
    assert "link 0->1 GB/s" in names
    links_pid = counters[0]["pid"]
    assert links_pid == max(_attribution_world()) + 1
    meta = [e for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("pid") == links_pid
            and e.get("name") == "process_name"]
    assert meta and meta[0]["args"]["name"] == "links"
    for e in counters:
        assert e["args"]["gbps"] > 0


def test_edge_betas_skips_unfit_edges_not_keyerror():
    """Satellite (PR 18): a partial probe map — an edge whose fit
    failed or produced a non-positive beta — is skipped by
    ``edge_betas``, never a KeyError downstream."""
    topo = skewed_topo(world=4, slow=())
    del topo["edges"]["0->1"]["beta_gbps"]
    topo["edges"]["1->2"]["beta_gbps"] = 0.0
    topo["edges"]["2->3"]["beta_gbps"] = "broken"
    betas = topology.edge_betas(topo)
    for gone in ((0, 1), (1, 2), (2, 3)):
        assert gone not in betas
    assert betas[(3, 0)] == pytest.approx(20.0)


def test_attribute_links_warns_and_counts_missing_probe_edges(capfd):
    """Satellite (PR 18): attribution against a probe map that does
    not cover every decomposed edge is a warned skip counted in
    ``missing_edges`` — the join must not crash on a shrunk world or
    failed fit."""
    topo = skewed_topo(world=4, slow=())
    del topo["edges"]["0->1"]
    out = topology.attribute_links(_attribution_world(), topo=topo)
    assert out["missing_edges"] == 1
    row = out["links"]["0->1"]
    assert row["gbps_p50"] > 0  # the sample itself still attributes
    assert "beta_gbps" not in row and "vs_probe" not in row
    covered = out["links"]["1->2"]
    assert covered["beta_gbps"] == pytest.approx(20.0)
    err = capfd.readouterr().err
    assert "not in the probe map" in err and "0->1" in err
    # a fully covered map reports zero missing and stays quiet
    out2 = topology.attribute_links(
        _attribution_world(), topo=skewed_topo(world=4, slow=())
    )
    assert out2["missing_edges"] == 0
    assert "not in the probe map" not in capfd.readouterr().err


# ---------------------------------------------------------------------
# planner consumption: the acceptance flip
# ---------------------------------------------------------------------


FLIP_KEY = planmod.plan_key(
    "AllReduce", nbytes=12 << 20, dtype="float32", world=8,
    axes=("a", "b"), platform="cpu",
)
FLIP_MESH = {"a": 2, "b": 4}


def _crossing_topo():
    model = topology.SyntheticLinkModel(
        8, beta_gbps=20.0,
        links={(0, 4): {"beta_gbps": 0.5}, (4, 0): {"beta_gbps": 0.5}},
    )
    return topology.synthetic_map(model)


def test_sweep_topo_flips_impl_choice():
    """Acceptance: a synthetic skewed topology measurably changes the
    planner's impl choice vs the uniform-peak analytic seed, and the
    winner records where its beta came from."""
    plan_uniform, _ = autotune.sweep([FLIP_KEY], mesh=FLIP_MESH, gbps=20.0)
    plan_topo, report = autotune.sweep(
        [FLIP_KEY], mesh=FLIP_MESH, gbps=20.0, topo=_crossing_topo())
    uniform_entry = plan_uniform.entries[FLIP_KEY]
    topo_entry = plan_topo.entries[FLIP_KEY]
    # pin the exact flip: the uniform seed picks the hierarchical
    # reduction (it minimizes steps), the skewed map rejects it
    # because its slow phase rides the planted 0.5 GB/s crossing
    assert uniform_entry.impl == "hierarchical"
    assert topo_entry.impl != "hierarchical"
    assert uniform_entry.beta_source is None
    assert topo_entry.beta_source == "topo-probe"
    (row,) = [r for r in report if r["key"] == FLIP_KEY]
    priced = [c for c in row["candidates"] if c["topo_s"] is not None]
    assert priced, row
    hier = [c for c in row["candidates"] if c["impl"] == "hierarchical"]
    assert hier and (hier[0]["pruned"] or hier[0]["topo_s"]
                     > min(c["topo_s"] for c in priced))


def test_sweep_measured_attribution_overrides_topo():
    # measured attribution data wins over probe-derived pricing, and
    # the provenance pin says so
    table = {
        "schema": autotune.TABLE_SCHEMA,
        "gbps": {},
        "keys": {FLIP_KEY: {"hierarchical": 100.0}},
        "sources": {"gbps": {}, "keys": {FLIP_KEY: {"hierarchical":
                                                    "attribution"}}},
    }
    plan_both, _ = autotune.sweep(
        [FLIP_KEY], mesh=FLIP_MESH, gbps=20.0, topo=_crossing_topo(),
        measured=table,
    )
    entry = plan_both.entries[FLIP_KEY]
    assert entry.impl == "hierarchical"
    assert entry.source == "measured"
    assert entry.beta_source == "attribution"


def test_plan_entry_beta_source_roundtrip():
    entry = planmod.PlanEntry(
        impl="hlo", source="analytic", expected_gbps=5.0,
        beta_source="topo-probe",
    )
    again = planmod.PlanEntry.from_json(entry.to_json())
    assert again.beta_source == "topo-probe"
    # absent stays absent: old plan files keep loading and old plan
    # fingerprints stay stable
    legacy = planmod.PlanEntry(impl="hlo", source="analytic")
    assert "beta_source" not in legacy.to_json()
    assert planmod.PlanEntry.from_json(legacy.to_json()).beta_source is None


def test_bwtable_sources_schema_pin(tmp_path):
    """The extended ``m4t-bwtable/1`` layout: float rows unchanged
    (old readers keep working), provenance in a parallel ``sources``
    mirror stamped ``attribution``."""
    world, nbytes = 2, 1 << 20
    for r in range(world):
        with open(tmp_path / f"events-rank{r}.jsonl", "w") as f:
            for rec in [
                {"kind": "emission", "rank": r, "seq": 1, "op": "AllReduce",
                 "shape": [nbytes // 4], "dtype": "float32",
                 "axes": ["ranks"], "world": world, "bytes": nbytes,
                 "cid": "c0001", "t": 100.0},
                {"kind": "latency", "rank": r, "op": "AllReduce",
                 "seconds": 1e-3, "cid": "c0001", "t": 100.1},
            ]:
                f.write(json.dumps(rec) + "\n")
    table = autotune.measured_table_from_events(
        [str(tmp_path)], platform="cpu")
    assert table["schema"] == "m4t-bwtable/1"
    assert sorted(table) == ["gbps", "keys", "schema", "sources"]
    assert table["keys"], table
    assert sorted(table["sources"]) == ["gbps", "keys"]
    for impl, src in table["sources"]["gbps"].items():
        assert src == "attribution"
        assert isinstance(table["gbps"][impl], float)
    for key, impls in table["sources"]["keys"].items():
        assert set(impls.values()) == {"attribution"}
        assert set(table["keys"][key]) == set(impls)


# ---------------------------------------------------------------------
# peak_gbps env fallback (costmodel satellite)
# ---------------------------------------------------------------------


@pytest.mark.parametrize("raw", ["abc", "-3"])
def test_peak_gbps_bad_env_warns_once_and_falls_back(monkeypatch, raw):
    monkeypatch.setenv("M4T_PEAK_GBPS", raw)
    monkeypatch.setattr(costmodel, "_WARNED_PEAK", set())
    with pytest.warns(RuntimeWarning, match="M4T_PEAK_GBPS"):
        got = costmodel.peak_gbps("tpu v5e")
    # the typo'd override must not poison the figure: generation table
    assert got == costmodel.ICI_PEAK_GBPS["v5e"]
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call: warn-once
        assert costmodel.peak_gbps("tpu v5e") == got


def test_peak_gbps_empty_env_is_silent(monkeypatch):
    monkeypatch.setenv("M4T_PEAK_GBPS", "")
    monkeypatch.setattr(costmodel, "_WARNED_PEAK", set())
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert costmodel.peak_gbps("tpu v5e") == (
            costmodel.ICI_PEAK_GBPS["v5e"])
    monkeypatch.setenv("M4T_PEAK_GBPS", "123.5")
    assert costmodel.peak_gbps() == 123.5


# ---------------------------------------------------------------------
# CLI: selftest, probe -> report -> tune --topo round trip
# ---------------------------------------------------------------------


def _topology_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.topology",
         *args],
        capture_output=True, text=True, cwd=cwd,
    )


def test_cli_selftest():
    res = _topology_cli("--selftest")
    assert res.returncode == 0, res.stderr
    assert "topology selftest ok" in res.stdout


def test_cli_probe_report_tune_roundtrip(tmp_path):
    res = _topology_cli(
        "probe", "--synthetic", "beta=20,0->4=0.5,4->0=0.5",
        "--world", "8", "--out", str(tmp_path),
    )
    assert res.returncode == 0, res.stderr
    mappath = str(tmp_path / topology.MAP_BASENAME)
    topo = topology.load(mappath)
    assert topo["world"] == 8

    res = _topology_cli("report", mappath)
    assert res.returncode == 0, res.stderr
    assert "0->4" in res.stdout and "slow links" in res.stdout

    res = _topology_cli("diff", mappath, mappath)
    assert res.returncode == 0, res.stderr

    tune = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", "tune",
         "--dry-run", "--json", "--world", "8", "--axes", "a,b",
         "--mesh", "a=2,b=4", "--ops", "AllReduce",
         "--topo", mappath],
        capture_output=True, text=True, cwd=REPO,
    )
    assert tune.returncode == 0, tune.stderr
    doc = json.loads(tune.stdout)
    entries = doc["plan"]["entries"]
    assert any(e.get("beta_source") == "topo-probe"
               for e in entries.values()), entries
    assert "pricing candidates over" in tune.stderr

    # a bad map is a clean exit-2, not a traceback
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    tune = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", "tune",
         "--dry-run", "--topo", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert tune.returncode == 2
    assert "--topo" in tune.stderr


# ---------------------------------------------------------------------
# end-to-end: a real 2-rank probe on CPU
# ---------------------------------------------------------------------


needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


@needs_native
def test_launch_probe_topology_e2e(tmp_path):
    """A real ``launch -n 2 --probe-topology`` world: the probe
    sendrecv sweep runs before the workload, persists a validated
    ``m4t-topo/1`` map with finite positive fitted betas for both
    directed edges, and the workload still completes."""
    rundir = str(tmp_path / "run")
    path = str(tmp_path / "case.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent("""
            import jax.numpy as jnp
            import mpi4jax_tpu as m4t
            from mpi4jax_tpu.runtime import shm
            x = m4t.allreduce(jnp.arange(4.0) + shm.rank())
            m4t.barrier()
            print(f"OK{shm.rank()}")
        """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--events-dir", rundir, "--probe-topology", path],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    assert "OK0" in res.stdout and "OK1" in res.stdout
    assert "topology probe" in res.stderr
    topo = topology.load(os.path.join(rundir, topology.MAP_BASENAME))
    assert topo["world"] == 2
    assert set(topo["edges"]) == {"0->1", "1->0"}
    for edge in topo["edges"].values():
        assert math.isfinite(edge["beta_gbps"]) and edge["beta_gbps"] > 0
        assert math.isfinite(edge["alpha_s"]) and edge["alpha_s"] >= 0
        assert edge["samples"] >= 3
        assert edge["provenance"].startswith("probe:")
    # the probed map feeds straight into the offline doctor join
    report = doctor.diagnose([rundir])
    doctor.attach_link_classification(report, topo)


def test_probe_topology_requires_events_dir():
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--probe-topology", "nosuch.py"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 2
    assert "--events-dir" in res.stderr


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            json.dump(golden_topo(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"regenerated {GOLDEN}")
    else:
        sys.exit(pytest.main([__file__, "-v"]))
