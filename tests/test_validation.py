"""``validation.enforce_types``: numpy-scalar normalization regression
(ISSUE 3 satellite).

The docstring always promised numpy-style scalar ints are "accepted
transparently by normalizing", but the check was a bare ``isinstance``
— and ``np.int64`` does **not** subclass ``int`` on 64-bit Linux, so
``bcast(x, root=np.int64(0))`` (the result of any numpy index
arithmetic) raised TypeError. Now the wrapper really normalizes:
the wrapped function receives genuine ``int``/``bool`` values."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu.validation import enforce_types


@enforce_types(root=int, flag=bool, comm=(type(None), m4t.Comm))
def probe(root, flag=False, comm=None):
    return root, flag


def test_python_scalars_pass_through():
    assert probe(3, flag=True) == (3, True)


@pytest.mark.parametrize(
    "value", [np.int8(3), np.int32(3), np.int64(3), np.uint16(3)]
)
def test_numpy_ints_normalized_where_int_allowed(value):
    root, _ = probe(value)
    assert root == 3
    assert type(root) is int  # really normalized, not just accepted


def test_numpy_bool_normalized_where_bool_allowed():
    _, flag = probe(0, flag=np.bool_(True))
    assert flag is True
    assert type(flag) is bool


def test_numpy_bool_normalizes_to_int_when_only_int_allowed():
    @enforce_types(n=int)
    def g(n):
        return n

    out = g(np.bool_(True))
    assert out == 1 and type(out) is int


def test_numpy_float_still_rejected():
    with pytest.raises(TypeError, match="must be of type"):
        probe(np.float32(3.0))


def test_traced_value_still_gets_dedicated_error():
    with pytest.raises(TypeError, match="static"):
        jax.jit(lambda r: probe(r))(jnp.asarray(0))


def test_wrong_type_still_rejected():
    with pytest.raises(TypeError, match="must be of type"):
        probe("zero")


def test_numpy_int_not_accepted_where_only_bool_allowed():
    @enforce_types(flag=bool)
    def g(flag):
        return flag

    with pytest.raises(TypeError, match="must be of type"):
        g(np.int32(1))


def test_bcast_accepts_numpy_root_end_to_end(run_spmd, per_rank):
    # the real-world shape of the bug: a root index produced by numpy
    # arithmetic (np.argmax and friends return np.int64)
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(
        lambda x: m4t.bcast(x, root=np.int64(2)), arr.astype(np.float32)
    )
    np.testing.assert_allclose(out, np.full_like(arr, 2.0))


def test_unknown_argument_name_rejected_at_decoration():
    with pytest.raises(ValueError, match="no argument"):
        enforce_types(nope=int)(lambda x: x)
