"""Integration tests running the bundled examples end-to-end as real
CLI programs (reference ``tests/test_examples.py:20-24`` runs the
shallow-water demo for a model day)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, *args, timeout=280):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


@pytest.mark.parametrize("nproc", ["1", "8"])
def test_shallow_water_example(nproc):
    res = run_example(
        "shallow_water.py",
        "--benchmark", "--nproc", nproc, "--days", "0.02", "--platform", "cpu",
    )
    assert res.returncode == 0, res.stderr
    assert "Solution took" in res.stderr
    assert "steps/s" in res.stderr


def test_shallow_water_rows_fused_probe_gated():
    # --decomp rows routes through the deep-halo fused stepper only
    # after the 3-step on-mesh equivalence probe passes (ADVICE r3:
    # the rows path used to route unconditionally)
    res = run_example(
        "shallow_water.py",
        "--benchmark", "--nproc", "4", "--days", "0.02",
        "--platform", "cpu", "--decomp", "rows", "--fused", "on",
        timeout=560,
    )
    assert res.returncode == 0, res.stderr
    assert "deep-halo fused step verified on-mesh" in res.stderr
    assert "Solution took" in res.stderr


def test_shallow_water_2d_fused_probe_gated():
    # the default (2, n/2) reference layout routes through the 2-D
    # deep-halo fused stepper behind the same probe gate (VERDICT r3
    # next #4: the reference's own benchmark layout silently couldn't
    # use the fused SPMD step)
    res = run_example(
        "shallow_water.py",
        "--benchmark", "--nproc", "4", "--days", "0.02",
        "--platform", "cpu", "--fused", "on",
        timeout=560,
    )
    assert res.returncode == 0, res.stderr
    assert "deep-halo fused step verified on-mesh" in res.stderr
    assert "dims (2, 2)" in res.stderr
    assert "Solution took" in res.stderr


def test_transformer_example_ring():
    res = run_example(
        "train_transformer.py",
        "--nproc", "4", "--steps", "8", "--platform", "cpu",
    )
    assert res.returncode == 0, res.stderr
    assert "steps/s" in res.stderr


def test_transformer_example_checkpoint_resume(tmp_path):
    """The resume-aware loop: run to step 7 with checkpoints, then a
    second invocation with --resume continues from the newest valid
    checkpoint instead of step 0 (the M4T_RESUME_STEP path is driven
    by the launch supervisor; tests/test_resilience.py covers it)."""
    pytest.importorskip("orbax.checkpoint")
    ckpt = str(tmp_path / "ckpt")
    res = run_example(
        "train_transformer.py",
        "--nproc", "2", "--steps", "7", "--platform", "cpu",
        "--ckpt-dir", ckpt, "--ckpt-every", "3",
    )
    assert res.returncode == 0, res.stderr
    saved = sorted(os.listdir(ckpt))
    assert "step_00000002" in saved and "step_00000006" in saved
    res2 = run_example(
        "train_transformer.py",
        "--nproc", "2", "--steps", "10", "--platform", "cpu",
        "--ckpt-dir", ckpt, "--resume",
    )
    assert res2.returncode == 0, res2.stderr
    assert "resumed from checkpoint step 6" in res2.stderr
    assert "step   9" in res2.stderr  # continued to the new horizon
    assert "step   0" not in res2.stderr  # ...without restarting
    assert "3 steps in" in res2.stderr  # exactly steps 7..9 ran


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.elastic
def test_transformer_elastic_preempt_resume_smaller_world(tmp_path):
    """ISSUE-9 satellite: a 4-rank train_transformer is preempted
    (SIGTERM) mid-run, checkpoints in its grace window, and resumes at
    2 ranks from the same (world-independent m4t-ckpt/2) checkpoint —
    with ``--seq-total`` holding the training problem fixed, the
    resumed loss curve stays within noise of an uninterrupted 2-rank
    run."""
    import re
    import signal
    import subprocess as sp

    ck = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    common = ["--steps", "12", "--platform", "cpu", "--seq-total", "64"]

    # uninterrupted 2-rank reference
    ref = run_example(
        "train_transformer.py", "--nproc", "2", *common,
    )
    assert ref.returncode == 0, ref.stderr
    ref_loss = float(
        re.search(r"loss [\d.]+ -> ([\d.]+)", ref.stderr).group(1))

    # 4-rank run, preempted once it reports step 5
    p = sp.Popen(
        [sys.executable,
         os.path.join(REPO, "examples", "train_transformer.py"),
         "--nproc", "4", *common, "--ckpt-dir", ck, "--ckpt-every", "4"],
        stderr=sp.PIPE, text=True, cwd=REPO, env=env,
    )
    lines = []
    for line in p.stderr:
        lines.append(line)
        if line.startswith("step   5"):
            p.send_signal(signal.SIGTERM)
    rc = p.wait(timeout=280)
    stderr0 = "".join(lines)
    assert rc == 143, (rc, stderr0)
    assert "preemption notice" in stderr0
    m = re.search(r"preempted: checkpointed step (\d+)", stderr0)
    assert m, stderr0

    # resume at 2 ranks: same global problem, world-mismatched ckpt
    res = run_example(
        "train_transformer.py", "--nproc", "2", *common,
        "--ckpt-dir", ck, "--resume",
    )
    assert res.returncode == 0, res.stderr
    assert "elastic resume" in res.stderr
    assert f"resumed from checkpoint step {m.group(1)}" in res.stderr
    got_loss = float(
        re.search(r"loss [\d.]+ -> ([\d.]+)", res.stderr).group(1))
    # same schedule, different world for the first half: reduction
    # order differs, convergence must not
    assert abs(got_loss - ref_loss) < 0.15 * max(ref_loss, 0.1), (
        got_loss, ref_loss)


def test_bench_smoke():
    env = dict(os.environ)
    env.update(M4T_BENCH_PLATFORM="cpu", M4T_BENCH_SCALE="1")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=280, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    import json

    line = [l for l in res.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "shallow_water_100x_solve"
    assert rec["unit"] == "s" and rec["value"] > 0


def test_cg_solver_example():
    # distributed CG: sendrecv-halo matvec + allreduce dot products in
    # a while_loop (the reference's CG-through-allreduce pattern,
    # tests/test_jax_transforms.py:6-22, as a full example app)
    res = run_example(
        "cg_solver.py",
        "--n", "256", "--nproc", "8", "--platform", "cpu",
        "--tol", "1e-6", "--max-iters", "2000",
    )
    assert res.returncode == 0, res.stderr
    assert "rel. error" in res.stdout


def test_zero_optimizer_example():
    # ZeRO-DP (reduce_scatter + shard update + allgather) must match
    # all-reduce DP step-for-step and reduce the loss
    res = run_example(
        "zero_optimizer.py",
        "--nproc", "8", "--platform", "cpu", "--steps", "30",
    )
    assert res.returncode == 0, res.stderr
    assert "matches all-reduce DP" in res.stdout
