"""Federated serving (ISSUE-14): server registry + leases, orphan
reclamation, zombie fencing, cross-server coordination.

Covers the ISSUE-14 acceptance surface:

- registry/leases: serve loops register under a unique ``server_id``,
  renew a heartbeat lease, deregister cleanly; ``servers()`` computes
  lease age and liveness against an injectable clock;
- claim ownership: a federated claim names its owner and claim epoch
  *in the running entry itself* (``<entry>@<server>@<epoch>``) so the
  scavenger and the fence work from disk alone;
- the claim race: N threads racing ``Spool.claim`` — every job is
  claimed exactly once, none lost, none duplicated, and the status
  totals are conserved;
- orphan reclamation: expired-lease and gone-server owners have their
  running entries requeued atomically with ``reclaims`` /
  ``reclaimed_from`` provenance; the per-job reclaim cap turns the
  job terminal (``failed: reclaim_exhausted``) instead of cycling
  forever; interrupted transitions (a finisher that died holding the
  atomic take) are swept;
- zombie fencing: a reclaimed-out server's late terminal record is
  rejected with a ``fenced`` audit naming the zombie and the current
  holder — no job goes terminal twice;
- reclaimed checkpointed jobs resume from their newest checkpoint on
  the surviving server (attempt 0 starts warm);
- cross-server coordination: a poisoned verdict recorded by server A
  is refused by server B (the verdict lives in the spool, not the
  pool);
- single-server byte-compat: legacy (unowned) claims are never
  touched by the scavenger, legacy ``finish`` still returns True, and
  a no-peer serve emits the PR 12 audit/terminal records plus only
  the additive registry events;
- ``submit --wait`` CLI exit codes (0 completed / 1 failed /
  3 rejected / 2 wait timeout);
- the doctor's failover narration and the federation OpenMetrics
  families;
- chaos e2e (slow, ``-m 'federation and chaos'``): two ``serve``
  processes, one SIGKILLed mid-job — the survivor reclaims the orphan
  and completes it *from its checkpoint*; every id ends terminal
  exactly once and an injected zombie write is fenced.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from mpi4jax_tpu.observability import doctor
from mpi4jax_tpu.resilience import ckpt as _ckpt
from mpi4jax_tpu.resilience.reshard import LeafSpec
from mpi4jax_tpu.serving import Server, Spool, parse_job
from mpi4jax_tpu.serving import export as sexport

pytestmark = [pytest.mark.serving, pytest.mark.federation]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(spool, *names):
    return [r for r in spool.audit_records() if r["event"] in names]


def _terminal(spool, job_id):
    return [r for r in spool.audit_records()
            if r["event"] in ("completed", "failed", "rejected")
            and r.get("job") == job_id]


# ---------------------------------------------------------------------
# registry + leases
# ---------------------------------------------------------------------


def test_registry_lease_lifecycle(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    rec = spool.register_server("sA", lease_s=10.0, now=100.0, world=2)
    assert rec["id"] == "sA" and rec["lease_s"] == 10.0
    (srv,) = spool.servers(now=104.0)
    assert srv["id"] == "sA" and srv["alive"]
    assert srv["lease_age_s"] == pytest.approx(4.0)
    # a renew resets the age; the lease stays alive past the original
    # expiry
    spool.renew_lease("sA", now=106.0)
    (srv,) = spool.servers(now=112.0)
    assert srv["alive"] and srv["lease_age_s"] == pytest.approx(6.0)
    # silence past the lease: still listed, no longer alive
    (srv,) = spool.servers(now=117.0)
    assert not srv["alive"]
    # a renew after the registry file was removed re-registers
    os.unlink(os.path.join(spool.root, "servers", "sA.json"))
    spool.renew_lease("sA", now=120.0)
    (srv,) = spool.servers(now=121.0)
    assert srv["alive"]
    spool.deregister_server("sA", jobs=0)
    assert spool.servers() == []
    assert _events(spool, "server_register")
    assert _events(spool, "server_stop")


def test_claim_records_owner_and_epoch(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "own", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    got = spool.claim(spec, server="sA")
    assert got is not None
    assert got.owner == "sA" and got.epoch == 1
    assert got.entry.endswith(".json@sA@1")
    # the race still has exactly one winner across servers
    assert spool.claim(spec, server="sB") is None
    (run,) = spool.running()
    assert run.owner == "sA" and run.epoch == 1
    (row,) = spool.status()["running"]
    assert row["server"] == "sA" and row["epoch"] == 1
    (rec,) = _events(spool, "claimed")
    assert rec["server"] == "sA" and rec["epoch"] == 1
    with pytest.raises(ValueError):
        spool.claim(spec, server="bad id!")


def test_claim_race_exactly_one_winner_per_job(tmp_path):
    """Property: N servers racing ``claim`` over M jobs — every job is
    claimed exactly once, none lost, none duplicated, and every winner
    can finish its own claim."""
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(64)
    jobs = [f"j{i:02d}" for i in range(12)]
    for j in jobs:
        assert spool.submit({"id": j, "cmd": ["-c", "pass"]})[
            "status"] == "queued"
    n = 6
    barrier = threading.Barrier(n)
    wins = [[] for _ in range(n)]
    errors = []

    def racer(i):
        try:
            specs = spool.pending()  # private spec objects per thread
            barrier.wait()
            for spec in specs:
                got = spool.claim(spec, server=f"s{i}")
                if got is not None:
                    wins[i].append(got)
        except Exception as exc:  # pragma: no cover - fail loudly
            errors.append(exc)

    threads = [threading.Thread(target=racer, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    claimed = [s.id for w in wins for s in w]
    assert sorted(claimed) == jobs  # exactly once each, none lost
    assert spool.pending() == []
    running = spool.running()
    assert sorted(s.id for s in running) == jobs
    assert all(s.owner is not None and s.epoch == 1 for s in running)
    assert spool.status()["depth"] == 0
    # every winner finishes its own claims — nothing is fenced
    for i, w in enumerate(wins):
        for spec in w:
            assert spool.finish(spec, "completed", server=f"s{i}",
                                epoch=spec.epoch)
    assert sorted(r["id"] for r in spool.done()) == jobs
    assert spool.running() == []


# ---------------------------------------------------------------------
# orphan reclamation
# ---------------------------------------------------------------------


def test_reclaim_requeues_expired_lease_with_provenance(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.register_server("sA", lease_s=1.0, now=100.0)
    assert spool.submit({"id": "orph", "tenant": "t",
                         "cmd": ["-c", "pass"]})["status"] == "queued"
    (spec,) = spool.pending()
    assert spool.claim(spec, server="sA") is not None
    # fresh lease: the scavenger does not touch it
    assert spool.reclaim(now=100.5, by="sB") == []
    # grace extends the lease
    assert spool.reclaim(now=102.0, by="sB", grace_s=10.0) == []
    # a server never reclaims its own claims
    assert spool.reclaim(now=102.0, by="sA") == []
    (act,) = spool.reclaim(now=102.0, by="sB")
    assert act["action"] == "requeued" and act["job"] == "orph"
    assert act["from_server"] == "sA" and act["epoch"] == 1
    assert act["reason"] == "lease_expired"
    assert spool.running() == []
    (back,) = spool.pending()
    assert back.reclaims == 1
    (prov,) = back.reclaimed_from
    assert prov["server"] == "sA" and prov["epoch"] == 1
    assert prov["reason"] == "lease_expired" and prov["by"] == "sB"
    (exp,) = _events(spool, "lease_expired")
    assert exp["server"] == "sA" and exp["by"] == "sB"
    (rec,) = _events(spool, "reclaim")
    assert rec["action"] == "requeued" and rec["reclaims"] == 1
    # the next claim runs at epoch 2: provenance feeds the fence
    assert spool.claim(back, server="sB").epoch == 2


def test_reclaim_cap_turns_terminal_not_cyclic(tmp_path):
    """A job whose every claimer dies must not cycle forever: past the
    cap it goes terminal ``failed: reclaim_exhausted`` exactly once."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "cyc", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    actions = []
    for _ in range(3):
        (spec,) = spool.pending()
        # "ghost" never registered: the owner is simply gone
        assert spool.claim(spec, server="ghost") is not None
        (act,) = spool.reclaim(now=200.0, by="sB", max_reclaims=2)
        actions.append(act["action"])
        assert act["reason"] == "server_gone"
    assert actions == ["requeued", "requeued", "exhausted"]
    assert spool.pending() == [] and spool.running() == []
    (rec,) = spool.done()
    assert rec["id"] == "cyc" and rec["outcome"] == "failed"
    assert rec["reason"] == "reclaim_exhausted"
    assert rec["reclaims"] == 2 and len(rec["reclaimed_from"]) == 2
    # terminal exactly once, and the audit says why
    (term,) = _terminal(spool, "cyc")
    assert term["event"] == "failed"
    assert term["reason"] == "reclaim_exhausted"
    # ghost was never registered: no lease_expired record for it
    assert _events(spool, "lease_expired") == []


def test_reclaim_sweeps_interrupted_transitions(tmp_path):
    """A finisher/scavenger that died *after* the atomic take but
    before its done/pending write leaves a token behind; once its
    creator's lease is gone the job is requeued, and tokens whose
    transition did complete are swept as litter."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "tok", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    assert spool.claim(spec, server="sA") is not None
    # simulate the crash: the take landed, the done record never did
    os.replace(
        os.path.join(spool.root, "running", spec.entry),
        os.path.join(spool.job_dir("tok"), ".terminal@sA@1"),
    )
    assert spool.running() == [] and spool.pending() == []
    (act,) = spool.reclaim(now=300.0, by="sB")
    assert act["action"] == "requeued"
    assert act["reason"] == "interrupted_transition"
    (back,) = spool.pending()
    assert back.id == "tok" and back.reclaims == 1
    # finish it properly, then sweep a stale leftover token as litter
    assert spool.claim(back, server="sB") is not None
    assert spool.finish(back, "completed", server="sB",
                        epoch=back.epoch)
    with open(os.path.join(spool.job_dir("tok"),
                           ".reclaim@sA@1"), "w") as f:
        json.dump(back.to_json(), f)
    (act,) = spool.reclaim(now=301.0, by="sB")
    assert act["action"] == "swept"
    assert not os.path.exists(
        os.path.join(spool.job_dir("tok"), ".reclaim@sA@1"))
    (rec,) = spool.done()
    assert rec["outcome"] == "completed"  # terminal exactly once


# ---------------------------------------------------------------------
# zombie fencing
# ---------------------------------------------------------------------


def test_zombie_finish_is_fenced(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.register_server("sA", lease_s=1.0, now=100.0)
    assert spool.submit({"id": "f0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    zombie = spool.claim(spec, server="sA")
    assert zombie is not None and zombie.epoch == 1
    # sA wedges; sB reclaims and re-claims at epoch 2
    (act,) = spool.reclaim(now=102.0, by="sB")
    assert act["action"] == "requeued"
    (back,) = spool.pending()
    winner = spool.claim(back, server="sB")
    assert winner.epoch == 2
    # the zombie wakes up and tries to write its stale terminal record
    assert spool.finish(zombie, "completed", server="sA",
                        epoch=1) is False
    (fen,) = _events(spool, "fenced")
    assert fen["job"] == "f0" and fen["server"] == "sA"
    assert fen["epoch"] == 1 and fen["outcome_rejected"] == "completed"
    assert fen["holder"] == {"server": "sB", "epoch": 2}
    assert spool.done() == []  # the zombie wrote nothing
    # the live claim finishes normally — exactly one terminal record
    assert spool.finish(winner, "failed", server="sB", epoch=2,
                        reason="oom") is True
    (rec,) = spool.done()
    assert rec["outcome"] == "failed" and rec["reclaims"] == 1
    # an even later zombie retry is fenced again, not double-written
    assert spool.finish(zombie, "completed", server="sA",
                        epoch=1) is False
    assert len(spool.done()) == 1


# ---------------------------------------------------------------------
# reclaimed jobs resume from their checkpoint
# ---------------------------------------------------------------------


def test_reclaimed_job_resumes_from_checkpoint(tmp_path):
    """The surviving server's in-loop scavenger reclaims the orphan
    and its attempt 0 starts from the newest checkpoint step."""
    spool = Spool(str(tmp_path / "sp"))
    ckroot = str(tmp_path / "ck")
    mgr = _ckpt.CheckpointManager(ckroot, keep=2, world=1)
    mgr.save_sharded(
        7, {"w": np.arange(4.0, dtype=np.float32)},
        {"w": LeafSpec(shape=(4,), dtype="float32")},
    )
    assert spool.submit({
        "id": "orph", "cmd": ["-c", "pass"], "resume_dir": ckroot,
    })["status"] == "queued"
    # sA claimed it and died long ago
    spool.register_server("sA", lease_s=1.0, now=time.time() - 60.0)
    (spec,) = spool.pending()
    assert spool.claim(spec, server="sA") is not None
    resumes = []

    def runner(spec, world, events_dir, attempt, resume_step):
        resumes.append(resume_step)
        return 0, []

    server = Server(
        spool, nproc=1, max_jobs=1, poll_s=0.01, runner=runner,
        server_id="sB", lease_s=0.2, log=lambda msg: None,
    )
    rc = server.serve()
    assert rc == 0
    assert resumes == [7]  # attempt 0 started warm
    (rec,) = spool.done()
    assert rec["id"] == "orph" and rec["outcome"] == "completed"
    assert rec["reclaims"] == 1
    assert rec["reclaimed_from"][0]["server"] == "sA"
    (adm,) = _events(spool, "admitted")
    assert adm["reclaims"] == 1 and adm["resume_step"] == 7
    (term,) = _terminal(spool, "orph")
    assert term["event"] == "completed"


def test_poison_verdict_is_spool_global(tmp_path):
    """Server A's strikes persist in the spool; server B refuses the
    job without ever dispatching it."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.record_strike("tox", reason="pool_wedged",
                               server="sA") == 1
    assert not spool.poisoned("tox")
    assert spool.record_strike("tox", reason="pool_wedged",
                               server="sA") == 2
    assert spool.poisoned("tox") and spool.strikes("tox") == 2
    (v,) = spool.verdicts()
    assert v["job"] == "tox" and v["poisoned"]
    assert spool.submit({"id": "tox", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    ran = []

    def runner(spec, world, events_dir, attempt, resume_step):
        ran.append(spec.id)
        return 0, []

    server = Server(spool, nproc=1, max_jobs=1, poll_s=0.01,
                    runner=runner, server_id="sB",
                    log=lambda msg: None)
    assert server.serve() == 0
    assert ran == []  # never dispatched
    (rec,) = spool.done()
    assert rec["outcome"] == "failed" and rec["reason"] == "poisoned"
    (term,) = _terminal(spool, "tox")
    assert term["reason"] == "poisoned" and term["refused"] is True


# ---------------------------------------------------------------------
# single-server byte-compat (PR 12 pin)
# ---------------------------------------------------------------------


def test_legacy_unowned_claims_are_untouched(tmp_path):
    """Old spools stay readable and old call sites stay correct: an
    unowned claim is invisible to the scavenger and legacy ``finish``
    still returns True with the PR 12 record shape."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "old", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    assert spool.claim(spec) is not None  # no server=: legacy
    assert "@" not in spec.entry
    assert spec.owner is None and spec.epoch is None
    # the scavenger never touches unowned entries, however old
    assert spool.reclaim(now=time.time() + 9999.0, by="sX") == []
    (run,) = spool.running()
    assert run.id == "old"
    assert spool.finish(spec, "completed", world=1) is True
    (rec,) = spool.done()
    assert rec["outcome"] == "completed"
    for k in ("reclaims", "reclaimed_from", "owner", "epoch"):
        assert k not in rec, k


def test_single_server_serve_matches_pr12_records(tmp_path):
    """A no-peer serve writes the same audit event sequence and
    terminal records as PR 12; the only additions are the registry
    events, and no failover event ever fires."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "ok", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    assert spool.submit({"id": "bad", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    spool.request_drain()

    def runner(spec, world, events_dir, attempt, resume_step):
        return (1, []) if spec.id == "bad" else (0, [])

    server = Server(spool, nproc=1, max_jobs=2, poll_s=0.01,
                    runner=runner, server_id="solo",
                    log=lambda msg: None)
    assert server.serve() == 0
    recs = spool.audit_records()
    events = [r["event"] for r in recs]
    # federation never fires without a dead peer
    for absent in ("reclaim", "fenced", "lease_expired"):
        assert absent not in events, absent
    # the additions are exactly the registry bookends
    added = [e for e in events if e in ("server_register",
                                       "server_stop")]
    assert added == ["server_register", "server_stop"]
    # everything else is the PR 12 sequence, in the PR 12 order
    assert [e for e in events if e not in ("server_register",
                                           "server_stop")] == [
        "submitted", "submitted", "drain_requested", "serve_start",
        "claimed", "admitted", "completed",
        "claimed", "admitted", "failed",
    ]
    # terminal records keep the PR 12 shape: no federation keys at all
    for rec in spool.done():
        for k in ("reclaims", "reclaimed_from", "owner", "epoch"):
            assert k not in rec, (rec["id"], k)
    assert {r["id"]: r["outcome"] for r in spool.done()} == {
        "ok": "completed", "bad": "failed",
    }


# ---------------------------------------------------------------------
# doctor narration + metrics export
# ---------------------------------------------------------------------


def _failover_flow(tmp_path):
    """register sA -> claim -> lease expires -> sB reclaims and
    completes at epoch 2 -> the sA zombie is fenced."""
    spool = Spool(str(tmp_path / "sp"))
    spool.register_server("sA", lease_s=1.0, now=100.0)
    assert spool.submit({"id": "f0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    zombie = spool.claim(spec, server="sA")
    spool.reclaim(now=102.0, by="sB")
    (back,) = spool.pending()
    winner = spool.claim(back, server="sB")
    assert spool.finish(zombie, "completed", server="sA",
                        epoch=1) is False
    assert spool.finish(winner, "completed", server="sB", epoch=2,
                        world=1, attempts=1)
    return spool


def test_doctor_narrates_failover(tmp_path):
    spool = _failover_flow(tmp_path)
    recs = doctor.load_serving_audit([spool.root])
    text = doctor.format_serving_timeline(recs)
    assert "server sA registered (lease 1.0s)" in text
    assert "claimed: job f0 by server sA (epoch 1)" in text
    assert "FAILOVER: server sA presumed dead" in text
    assert "detected by sB" in text
    assert ("FAILOVER: job f0 reclaimed from server sA (claim epoch "
            "1, lease_expired) by sB — requeued with provenance"
            ) in text
    assert ("FENCED: job f0 — zombie server sA (stale claim epoch 1) "
            "tried to write 'completed'; rejected "
            "(job now held by sB)") in text


def test_export_federation_metric_families(tmp_path):
    spool = _failover_flow(tmp_path)
    snap = sexport.serving_snapshot(spool)
    assert snap["reclaims"] == {"lease_expired": 1}
    assert snap["fenced"] == 1
    assert [s["id"] for s in snap["servers"]] == ["sA"]
    text = sexport.render_serving_metrics(snap)
    assert "m4t_serve_servers_alive 0" in text  # sA's lease is cold
    assert 'm4t_serve_server_lease_age{server="sA"}' in text
    assert 'm4t_serve_reclaims_total{reason="lease_expired"} 1' in text
    assert "m4t_serve_fenced_total 1" in text
    assert text.endswith("# EOF\n")


# ---------------------------------------------------------------------
# submit --wait CLI
# ---------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env.setdefault("MPI4JAX_TPU_SKIP_VERSION_CHECK", "1")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _cli(*argv, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.serving", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=_cli_env(),
    )


def test_cli_submit_wait_timeout_without_server(tmp_path):
    sp = str(tmp_path / "sp")
    r = _cli("submit", sp, "--id", "w0", "--wait",
             "--wait-timeout", "0.4", "--", "-c", "pass")
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "not terminal after" in r.stderr


def test_cli_submit_wait_follows_the_outcome(tmp_path):
    sp = str(tmp_path / "sp")
    serve = subprocess.Popen(
        [sys.executable, "-m", "mpi4jax_tpu.serving", "serve", sp,
         "-n", "1", "--poll", "0.05"],
        cwd=REPO, env=_cli_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        r = _cli("submit", sp, "--id", "good", "--wait", "--",
                 "-c", "pass", timeout=240)
        assert r.returncode == 0, (r.stdout, r.stderr)
        # two JSON lines: the queued response, then the outcome
        queued, out = map(json.loads, r.stdout.splitlines())
        assert queued["status"] == "queued"
        assert out["job"] == "good" and out["outcome"] == "completed"
        r = _cli("submit", sp, "--id", "sad", "--wait", "--",
                 "-c", "import sys; sys.exit(3)", timeout=240)
        assert r.returncode == 1, (r.stdout, r.stderr)
        assert json.loads(r.stdout.splitlines()[-1])[
            "outcome"] == "failed"
        # a rejected submit exits 3 immediately, wait or not
        r = _cli("submit", sp, "--id", "good", "--wait", "--",
                 "-c", "pass")
        assert r.returncode == 3
        assert json.loads(r.stdout)["reason"] == "duplicate_id"
    finally:
        _cli("drain", sp)
        try:
            serve.wait(120)
        except subprocess.TimeoutExpired:
            serve.kill()
            raise


def test_cli_offline_reclaim(tmp_path):
    sp = str(tmp_path / "sp")
    spool = Spool(sp)
    spool.register_server("sA", lease_s=0.1,
                          now=time.time() - 60.0)
    assert spool.submit({"id": "r0", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    assert spool.claim(spec, server="sA") is not None
    r = _cli("reclaim", sp, "--by", "operator", "--json")
    assert r.returncode == 0, r.stderr
    (act,) = json.loads(r.stdout)
    assert act["job"] == "r0" and act["action"] == "requeued"
    (back,) = spool.pending()
    assert back.reclaims == 1
    # idempotent: a second pass finds nothing to do
    r = _cli("reclaim", sp, "--by", "operator", "--json")
    assert json.loads(r.stdout) == []


# ---------------------------------------------------------------------
# chaos e2e: SIGKILL one of two servers mid-job
# ---------------------------------------------------------------------

# device-free single-rank job: checkpoints every step, proves a warm
# resume by writing the step it came back from
_CKPT_JOB = """
import sys
import time
import numpy as np
from mpi4jax_tpu.resilience import ckpt, reshard, resume_step

ckroot, proof = sys.argv[1], sys.argv[2]
STEPS = 24
specs = {"w": reshard.LeafSpec(shape=(4,), dtype="float32")}
mgr = ckpt.CheckpointManager(ckroot, keep=3, world=1)
start = 0
r = resume_step()
if r is not None:
    with open(proof, "w") as f:
        f.write(f"resumed@{r}")
    start = r + 1
w = np.zeros(4, np.float32)
for step in range(start, STEPS):
    mgr.save_sharded(step, {"w": w + step}, specs)
    time.sleep(0.25)
"""


def _wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_sigkill_failover_loses_no_job(tmp_path):
    """ISSUE-14 acceptance: two ``serve`` processes over one spool;
    the one holding the job is SIGKILLed mid-run. The survivor's
    scavenger reclaims the orphan after the lease expires and
    completes it *from its checkpoint* (the proof file names the
    resumed step). Every submitted id ends terminal exactly once, and
    an injected zombie write from the dead server's identity is
    fenced, not recorded."""
    script = str(tmp_path / "ckpt_job.py")
    with open(script, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(_CKPT_JOB))
    sp = str(tmp_path / "sp")
    ckroot = str(tmp_path / "ck")
    proof = str(tmp_path / "proof.txt")
    spool = Spool(sp)
    assert spool.submit({
        "id": "orph", "cmd": [script, ckroot, proof],
        "resume_dir": ckroot, "timeout_s": 120.0,
    })["status"] == "queued"

    def serve(server_id, log_path):
        return subprocess.Popen(
            [sys.executable, "-m", "mpi4jax_tpu.serving", "serve", sp,
             "-n", "1", "--poll", "0.05", "--server-id", server_id,
             "--lease", "0.5"],
            cwd=REPO, env=_cli_env(), start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=open(log_path, "w"),
        )

    p1 = serve("chaos-s1", str(tmp_path / "s1.log"))
    p2 = None
    try:
        # wait until s1 owns the job AND a checkpoint step committed
        _wait_for(
            lambda: any(r["event"] == "claimed"
                        and r.get("server") == "chaos-s1"
                        for r in spool.audit_records()),
            60, "chaos-s1 to claim the job",
        )
        _wait_for(
            lambda: _ckpt.CheckpointManager(
                ckroot, world=1).latest_valid(world=1) is not None,
            60, "the first committed checkpoint",
        )
        # SIGKILL the whole process group: server AND its spawned job
        os.killpg(os.getpgid(p1.pid), signal.SIGKILL)
        p1.wait(30)
        p2 = serve("chaos-s2", str(tmp_path / "s2.log"))
        _wait_for(lambda: len(spool.done()) == 1, 120,
                  "the survivor to reclaim and complete the job")
    finally:
        for p in (p1, p2):
            if p is not None and p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except OSError:
                    pass
    _cli("drain", sp)
    if p2 is not None:
        p2.wait(120)

    # the survivor completed the orphan from its checkpoint
    (rec,) = spool.done()
    assert rec["id"] == "orph" and rec["outcome"] == "completed"
    assert rec["reclaims"] == 1
    assert rec["reclaimed_from"][0]["server"] == "chaos-s1"
    with open(proof) as f:
        body = f.read()
    assert body.startswith("resumed@"), body
    assert int(body.split("@")[1]) >= 0
    # the failover is fully audited…
    events = [r["event"] for r in spool.audit_records()]
    assert "lease_expired" in events
    (rcl,) = _events(spool, "reclaim")
    assert rcl["action"] == "requeued"
    assert rcl["from_server"] == "chaos-s1" and rcl["by"] == "chaos-s2"
    claims = _events(spool, "claimed")
    assert [(c["server"], c["epoch"]) for c in claims] == [
        ("chaos-s1", 1), ("chaos-s2", 2),
    ]
    # …and every id is terminal exactly once
    (term,) = _terminal(spool, "orph")
    assert term["event"] == "completed"

    # the dead server's identity comes back as a zombie: its late
    # terminal write must be fenced, never double-recorded
    zombie = parse_job({"id": "orph", "cmd": [script, ckroot, proof]})
    zombie.entry = f"{0:020d}-orph.json"
    assert spool.finish(zombie, "completed", server="chaos-s1",
                        epoch=1) is False
    (fen,) = _events(spool, "fenced")
    assert fen["job"] == "orph" and fen["server"] == "chaos-s1"
    assert len(spool.done()) == 1
    assert len(_terminal(spool, "orph")) == 1
