"""Tier-1 self-lint smoke test + golden JSON schema pin.

ISSUE 3 satellites: ``examples/`` and ``mpi4jax_tpu/models/`` must
lint clean (their ``M4T_LINT_TARGETS`` declare the per-rank entry
points with abstract shapes), and the JSON report schema is pinned by
``tests/data/lint_golden.json`` — the exact reports for the fixed
fixture module ``tests/data/lint_fixture.py``. Regenerate after an
intentional schema change::

    python tests/test_analysis_selflint.py --regen
"""

import importlib
import json
import os
import sys

import pytest

from mpi4jax_tpu.analysis import lint_module, reports_to_json
from mpi4jax_tpu.analysis.__main__ import _import_target

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "data", "lint_fixture.py")
GOLDEN = os.path.join(HERE, "data", "lint_golden.json")

MODEL_MODULES = (
    "mpi4jax_tpu.models.mlp",
    "mpi4jax_tpu.models.attention",
    "mpi4jax_tpu.models.shallow_water",
)

EXAMPLE_FILES = (
    "examples/cg_solver.py",
    "examples/zero_optimizer.py",
    "examples/train_transformer.py",
    "examples/shallow_water.py",
)


@pytest.mark.parametrize("modname", MODEL_MODULES)
def test_models_lint_clean(modname):
    reports = lint_module(importlib.import_module(modname))
    assert reports, f"{modname} declares no M4T_LINT_TARGETS"
    for rep in reports:
        assert rep.error is None, f"{rep.target}: {rep.error}"
        assert rep.findings == [], (
            f"{rep.target} is not lint-clean:\n{rep.to_text()}"
        )
        assert rep.sites, f"{rep.target} traced no collectives at all?"


@pytest.mark.parametrize("relpath", EXAMPLE_FILES)
def test_examples_lint_clean(relpath):
    module, _fn = _import_target(os.path.join(REPO, relpath))
    reports = lint_module(module)
    assert reports, f"{relpath} declares no M4T_LINT_TARGETS"
    for rep in reports:
        assert rep.error is None, f"{rep.target}: {rep.error}"
        assert rep.findings == [], (
            f"{rep.target} is not lint-clean:\n{rep.to_text()}"
        )


def _normalize(obj, root):
    """Strip machine-specific path prefixes from every string so the
    golden file is location-independent."""
    if isinstance(obj, str):
        return obj.replace(root + os.sep, "")
    if isinstance(obj, list):
        return [_normalize(v, root) for v in obj]
    if isinstance(obj, dict):
        return {k: _normalize(v, root) for k, v in obj.items()}
    return obj


def _fixture_reports_json():
    module, _fn = _import_target(FIXTURE)
    obj = reports_to_json(lint_module(module))
    return json.loads(json.dumps(_normalize(obj, REPO), sort_keys=True))


def test_lint_golden_file():
    """The exact JSON report for the fixed fixture is pinned by a
    golden file — any schema drift must be an intentional, reviewed
    change (same pattern as tests/data/trace_golden.json)."""
    produced = _fixture_reports_json()
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert produced == golden


def test_fixture_reports_expected_shape():
    # belt and braces beyond the byte-level pin: the fixture's bad
    # target trips exactly M4T101 (+102 necessarily) and M4T106
    module, _fn = _import_target(FIXTURE)
    reports = {r.target.split(":")[-1]: r for r in lint_module(module)}
    assert reports["clean"].findings == []
    bad_codes = sorted({f.code for f in reports["divergent"].findings})
    assert bad_codes == ["M4T101", "M4T102", "M4T106"]


if __name__ == "__main__":
    # regenerate the golden file after an intentional schema change
    if "--regen" in sys.argv:
        with open(GOLDEN, "w") as f:
            json.dump(_fixture_reports_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"golden rewritten: {GOLDEN}")
    else:
        print("usage: python tests/test_analysis_selflint.py --regen")
