"""Jaxpr walker: nested control flow yields the expected
CollectiveSite sequences (ISSUE 3 satellite: scan-of-cond, while
body, remat, pjit-inside-pjit, custom-vjp wrapped collectives)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import mpi4jax_tpu as m4t
from mpi4jax_tpu.analysis import lint, trace_sites

N = 8
X = jnp.zeros((4,), jnp.float32)


def _ops(graph):
    return [s.op for s in graph.sites]


def _paths(graph):
    return [s.path for s in graph.sites]


def test_flat_sequence_in_program_order():
    def f(x):
        y = m4t.allreduce(x)
        z = m4t.allgather(y)
        return m4t.bcast(z, 0)

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert _ops(g) == ["AllReduce", "AllGather", "Bcast"]
    assert all(p == () for p in _paths(g))
    # fingerprints carry shape/dtype/axes in the recorder schema
    assert g.sites[0].fingerprint == "AllReduce[4:float32]@ranks"
    assert g.sites[0].world == N


def test_scan_of_cond_nesting():
    def f(x):
        def body(c, _):
            c = lax.cond(
                c.sum() > 0,
                lambda v: m4t.allreduce(v),
                lambda v: m4t.allreduce(v),
                c,
            )
            return c, None

        y, _ = lax.scan(body, x, None, length=3)
        return y

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    # one site per branch, each nested scan -> cond
    assert _ops(g) == ["AllReduce", "AllReduce"]
    assert _paths(g) == [("scan", "cond[0]"), ("scan", "cond[1]")]
    # identical branch sequences: a cond is recorded but matches
    assert len(g.conds) == 1
    seqs = [
        tuple(s.fingerprint for s in br) for br in g.conds[0].branch_sites
    ]
    assert seqs[0] == seqs[1]


def test_while_body_sites():
    def f(x):
        def cond(state):
            v, it = state
            return it < 3

        def body(state):
            v, it = state
            return m4t.allreduce(v), it + 1

        v, _ = lax.while_loop(cond, body, (x, jnp.asarray(0, jnp.int32)))
        return v

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert _ops(g) == ["AllReduce"]
    assert _paths(g) == [("while[body]",)]
    assert len(g.whiles) == 1
    assert not g.whiles[0].pred_tainted


def test_remat_sites():
    def f(x):
        return jax.checkpoint(lambda v: m4t.allreduce(v) * 2.0)(x)

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert _ops(g) == ["AllReduce"]
    assert _paths(g) == [("remat",)]


def test_pjit_inside_pjit():
    def f(x):
        inner = jax.jit(lambda q: m4t.allgather(q))
        return jax.jit(lambda v: inner(v) + 1.0)(x)

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert _ops(g) == ["AllGather"]
    (path,) = _paths(g)
    assert len(path) == 2 and all(p.startswith("pjit(") for p in path)


def test_custom_vjp_wrapped_collective():
    @jax.custom_vjp
    def cv(x):
        return m4t.allreduce(x)

    cv.defvjp(lambda x: (cv(x), None), lambda res, g: (g,))

    g = trace_sites(cv, (X,), axis_env={"ranks": N})
    assert _ops(g) == ["AllReduce"]
    assert _paths(g) == [("custom_vjp",)]


def test_grad_through_collective_records_tangent_sites():
    # AD introduces extra binds (JVP of allreduce is allreduce of the
    # tangents); the walker must see them all and M4T104 must NOT fire
    # (the forward emissions carry the barrier chain).
    def f(x):
        return m4t.allreduce(x).sum()

    rep = lint(jax.grad(f), (X,), axis_env={"ranks": N})
    assert [f_.code for f_ in rep.findings] == []
    assert len(rep.sites) >= 1


def test_rank_taint_through_carry_fixpoint():
    # rank enters the while carry through an arithmetic detour; the
    # fixpoint must still mark the predicate tainted
    def f(x):
        r = lax.axis_index("ranks").astype(jnp.float32)

        def cond(state):
            v, acc = state
            return acc < 10.0

        def body(state):
            v, acc = state
            return m4t.allreduce(v), acc + r

        v, _ = lax.while_loop(cond, body, (x, jnp.zeros(())))
        return v

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert len(g.whiles) == 1
    assert g.whiles[0].pred_tainted


def test_rank_uniform_predicate_not_tainted():
    # a predicate derived from an allreduced value is rank-uniform in
    # *value*, but the dataflow still passes through the rank-free
    # path here: no axis_index involved at all
    def f(x):
        s = m4t.allreduce(x).sum()

        def cond(state):
            v, it = state
            return it < 2

        def body(state):
            v, it = state
            return m4t.allreduce(v), it + 1

        v, _ = lax.while_loop(cond, body, (x + s, jnp.asarray(0, jnp.int32)))
        return v

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert not g.whiles[0].pred_tainted


def test_comm_get_rank_taints():
    def f(x):
        r = m4t.get_default_comm().Get_rank()
        return lax.cond(
            r == 0, lambda v: m4t.allreduce(v), lambda v: v, x
        )

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert len(g.conds) == 1
    assert g.conds[0].pred_tainted


def test_source_location_points_at_user_code():
    def f(x):
        return m4t.allreduce(x)  # the line the site must name

    g = trace_sites(f, (X,), axis_env={"ranks": N})
    assert "test_analysis_walker.py" in g.sites[0].source


def test_transpose_identity_is_not_a_site():
    # identity_with_allreduce_grad lowers to no communication; its
    # forward bind must not count as a collective site
    from mpi4jax_tpu.ops.allreduce import identity_with_allreduce_grad

    g = trace_sites(
        lambda x: identity_with_allreduce_grad(x),
        (X,),
        axis_env={"ranks": N},
    )
    assert g.sites == []


def test_shard_map_contributes_mesh_axes(mesh):
    from mpi4jax_tpu.parallel import spmd

    rep = lint(
        spmd(lambda x: m4t.allreduce(x), mesh=mesh),
        (np.zeros((N, 4), np.float32),),
        axis_env={},
    )
    assert rep.findings == []
    (site,) = rep.sites
    assert site.path[-1] == "shard_map"
    assert site.axes == ("ranks",)
