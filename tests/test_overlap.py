"""Overlap observatory (``mpi4jax_tpu/observability/overlap.py``):
per-step compute/communication occupancy attribution.

Covers the PR 19 acceptance surface:

- interval algebra: random interval sets decompose into
  ``compute_only + comm_exposed + comm_overlapped + idle`` that
  telescopes exactly to the step span (<= 1e-6 s residual) and is
  invariant under permutation of the input intervals;
- span API arming contract: ``obs.step_span()``/``obs.compute_span()``
  are one-falsy-check no-ops unarmed; armed they emit pinned ``step``
  and ``compute`` interval records and stamp ``step`` onto emission,
  ``exec``, and ``latency`` records — the *unarmed* schemas stay
  byte-identical (drift-pinned here, like the PR 11/12 pins);
- golden report: ``build_report`` over a pinned synthetic 2-rank world
  matches ``tests/data/overlap_golden.json`` key-for-key (regenerate
  with ``python -m tests.test_overlap`` after intentional changes);
- the CLI (``python -m mpi4jax_tpu.observability.overlap``):
  --selftest, and RUNDIR report in text and --json forms;
- cost model: ``overlappable_fraction`` / ``expected_exposed_s`` (the
  ``lint --cost`` exposed-time column);
- the confirmed-straggler re-permutation loop (ROADMAP item 1
  follow-on): ``placement.derive_from_verdicts`` over live verdicts +
  a probed map, the ``planner placement derive --from-verdicts`` CLI,
  and the launcher's ``_propose_placement`` supervisor audit;
- e2e (native toolchain): a 2-rank ``launch --overlap`` world whose
  injected ``slowdown`` fault provably moves communication time from
  ``comm_overlapped`` to ``comm_exposed``.
"""

import json
import os
import random
import subprocess
import sys
import textwrap

import pytest

from mpi4jax_tpu.observability import costmodel, doctor, events, overlap
from mpi4jax_tpu.observability import topology
from mpi4jax_tpu.planner import placement

pytestmark = pytest.mark.overlap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "overlap_golden.json")


# ---------------------------------------------------------------------
# interval algebra (property tests)
# ---------------------------------------------------------------------


def _random_intervals(rng, n, lo, hi):
    """Arbitrary intervals around [lo, hi]: overlapping, nested,
    empty, inverted, and partially outside the window."""
    out = []
    for _ in range(n):
        a = rng.uniform(lo - 1.0, hi + 1.0)
        b = a + rng.uniform(-0.3, (hi - lo) * 0.6 + 0.1)
        out.append((a, b))
    return out


PHASES = ("compute_only_s", "comm_exposed_s", "comm_overlapped_s", "idle_s")


def test_decompose_telescopes_on_random_interval_sets():
    rng = random.Random(190)
    for _ in range(300):
        t0 = rng.uniform(-5.0, 5.0)
        t1 = t0 + rng.uniform(0.0, 10.0)
        compute = _random_intervals(rng, rng.randint(0, 9), t0, t1)
        comm = _random_intervals(rng, rng.randint(0, 9), t0, t1)
        d = overlap.decompose(t0, t1, compute, comm)
        assert d["ok"], d
        assert d["residual_s"] <= overlap.SUM_TOLERANCE_S
        assert abs(sum(d[k] for k in PHASES) - d["span_s"]) \
            <= overlap.SUM_TOLERANCE_S
        for k in PHASES:
            assert d[k] >= -1e-9, (k, d)
        assert 0.0 <= d["coverage"] <= 1.0 + 1e-9


def test_decompose_is_permutation_invariant():
    rng = random.Random(191)
    for trial in range(50):
        t0, t1 = 0.0, 10.0
        compute = _random_intervals(rng, 7, t0, t1)
        comm = _random_intervals(rng, 7, t0, t1)
        base = overlap.decompose(t0, t1, compute, comm)
        for seed in (1, 2, 3):
            srng = random.Random(seed * 1000 + trial)
            c2, m2 = list(compute), list(comm)
            srng.shuffle(c2)
            srng.shuffle(m2)
            assert overlap.decompose(t0, t1, c2, m2) == base


def test_merge_yields_disjoint_sorted_union():
    rng = random.Random(192)
    for _ in range(100):
        ivs = _random_intervals(rng, rng.randint(0, 12), 0.0, 5.0)
        merged = overlap.merge(ivs)
        for (a, b) in merged:
            assert a < b
        for (_, b), (a2, _) in zip(merged, merged[1:]):
            assert b < a2  # strictly disjoint and sorted
        shuffled = list(ivs)
        rng.shuffle(shuffled)
        assert overlap.merge(shuffled) == merged


def test_decompose_known_geometry():
    # compute [0,6], comm [4,8] in a [0,10] step: 2s hidden, 2s exposed
    d = overlap.decompose(0.0, 10.0, [(0.0, 6.0)], [(4.0, 8.0)])
    assert d["compute_only_s"] == pytest.approx(4.0)
    assert d["comm_overlapped_s"] == pytest.approx(2.0)
    assert d["comm_exposed_s"] == pytest.approx(2.0)
    assert d["idle_s"] == pytest.approx(2.0)
    assert d["coverage"] == pytest.approx(0.8)
    assert overlap.occupancy_ratio(d) == pytest.approx(0.5)


# ---------------------------------------------------------------------
# span API: arming contract + unarmed drift pins
# ---------------------------------------------------------------------

#: the PR 11 unarmed schemas, pinned literally (as in test_spans.py):
#: the overlap observatory must not widen any *unarmed* record
UNARMED_EMISSION_KEYS = {
    "kind", "cid", "op", "bytes", "dtype", "axes", "world",
    "annotation", "shape", "t", "seq", "op_seq",
}
UNARMED_EXEC_FILE_KEYS = {"kind", "cid", "op", "seq", "t", "rank", "ts"}
UNARMED_LATENCY_FILE_KEYS = {
    "kind", "cid", "op", "seq", "seconds", "t", "rank", "ts",
}
STEP_FILE_KEYS = {"kind", "step", "t0", "t1", "t", "rank", "ts"}
COMPUTE_FILE_KEYS = {"kind", "step", "t0", "t1", "t", "rank", "ts"}


@pytest.fixture
def armed_sink(tmp_path):
    """A private JSONL sink + clean telemetry/overlap state; restores
    everything (including the module sink) afterwards."""
    from mpi4jax_tpu import observability as obs
    from mpi4jax_tpu.observability import metrics as metrics_mod

    path = str(tmp_path / "events-rank0.jsonl")
    prev_sink = events._sink
    prev_enabled = metrics_mod._enabled
    events._sink = events.EventLog(path)
    obs.reset()
    obs.enable(runtime=True)
    yield path
    overlap.arm(False)
    obs.reset()
    metrics_mod._enabled = prev_enabled
    events._sink.close()
    events._sink = prev_sink


def test_step_span_unarmed_is_a_noop(armed_sink):
    assert overlap.current_step() is None
    with overlap.step_span(step=5) as n:
        assert n is None
        assert overlap.current_step() is None
        with overlap.compute_span() as c:
            assert c is None
    assert events.read(armed_sink) == []


def test_step_span_armed_emits_pinned_records(armed_sink):
    overlap.arm(True)
    with overlap.step_span(step=7) as n:
        assert n == 7
        assert overlap.current_step() == 7
        with overlap.compute_span():
            pass
    assert overlap.current_step() is None
    compute, step = events.read(armed_sink)
    assert step["kind"] == "step" and step["step"] == 7
    assert set(step) == STEP_FILE_KEYS, sorted(step)
    assert step["t0"] <= compute["t0"] <= compute["t1"] <= step["t1"]
    assert compute["kind"] == "compute" and compute["step"] == 7
    assert set(compute) == COMPUTE_FILE_KEYS, sorted(compute)


def test_step_span_autonumbers_and_survives_exceptions(armed_sink):
    overlap.arm(True)
    with overlap.step_span() as a:
        pass
    with pytest.raises(RuntimeError):
        with overlap.step_span() as b:
            assert b == a + 1
            raise RuntimeError("boom")
    recs = events.read(armed_sink)
    assert [r["step"] for r in recs] == [a, a + 1]  # both spans recorded


def test_runtime_records_step_stamp_is_armed_only(armed_sink):
    from mpi4jax_tpu import observability as obs

    reg = obs.registry

    def one_op(cid):
        # the ops/_core.py prologue: the trace-time step stamp is
        # whatever step context is open (None unarmed / outside)
        rec = reg.record_emission(
            "AllReduce", nbytes=64, dtype="float32", axes=("ranks",),
            world=2, cid=cid, step=overlap.current_step(),
        )
        reg.mark_runtime_start(cid)
        reg.mark_runtime_end(cid, "AllReduce")
        return rec

    # unarmed: emission/exec/latency schemas byte-identical to PR 11
    em = one_op("c1")
    assert set(em) == UNARMED_EMISSION_KEYS, sorted(em)
    execs = [r for r in events.read(armed_sink) if r["kind"] == "exec"]
    lats = [r for r in events.read(armed_sink) if r["kind"] == "latency"]
    assert set(execs[0]) == UNARMED_EXEC_FILE_KEYS, sorted(execs[0])
    assert set(lats[0]) == UNARMED_LATENCY_FILE_KEYS, sorted(lats[0])

    # armed + inside a step: every runtime record gains exactly `step`
    overlap.arm(True)
    with overlap.step_span(step=3):
        em2 = one_op("c2")
    assert set(em2) == UNARMED_EMISSION_KEYS | {"step"}
    assert em2["step"] == 3
    execs = [r for r in events.read(armed_sink) if r["kind"] == "exec"]
    lats = [r for r in events.read(armed_sink) if r["kind"] == "latency"]
    assert set(execs[1]) == UNARMED_EXEC_FILE_KEYS | {"step"}
    assert set(lats[1]) == UNARMED_LATENCY_FILE_KEYS | {"step"}
    assert execs[1]["step"] == lats[1]["step"] == 3

    # armed but outside any span: back to the unarmed schema
    em3 = one_op("c3")
    assert set(em3) == UNARMED_EMISSION_KEYS, sorted(em3)


# ---------------------------------------------------------------------
# golden report (pinned synthetic 2-rank world)
# ---------------------------------------------------------------------


def synthetic_overlap_world():
    """Two identical ranks, two steps each, all timestamps pinned.

    Geometry per rank: step 0 = [100, 101) with compute [100, 100.85)
    and one fully-hidden + one exposed AllReduce; step 1 = [101, 102)
    with compute [101, 101.92) and one hidden AllReduce; one
    standalone AllReduce after the steps (the contention-free
    bandwidth cohort). Regenerate the golden with
    ``python -m tests.test_overlap`` after intentional changes."""
    world = {}
    for rank in (0, 1):
        ca, cb = f"c{rank}a", f"c{rank}b"
        world[rank] = [
            {"kind": "emission", "rank": rank, "seq": 1, "op": "AllReduce",
             "cid": ca, "bytes": 1 << 20, "dtype": "float32",
             "axes": ["ranks"], "world": 2, "shape": [262144],
             "impl": "pallas_ring", "plan": "cpu|AllReduce|f32|1048576|w2",
             "t": 100.0, "step": 0},
            {"kind": "emission", "rank": rank, "seq": 2, "op": "AllReduce",
             "cid": cb, "bytes": 1 << 20, "dtype": "float32",
             "axes": ["ranks"], "world": 2, "shape": [262144],
             "impl": "hlo", "plan": "cpu|AllReduce|f32|1048576|w2",
             "t": 100.1, "step": 0},
            {"kind": "step", "rank": rank, "step": 0,
             "t0": 100.0, "t1": 101.0, "t": 101.0},
            {"kind": "compute", "rank": rank, "step": 0,
             "t0": 100.0, "t1": 100.85, "t": 100.85},
            {"kind": "latency", "rank": rank, "cid": ca, "op": "AllReduce",
             "seq": 1, "seconds": 0.2, "t": 100.7, "step": 0},
            {"kind": "latency", "rank": rank, "cid": cb, "op": "AllReduce",
             "seq": 2, "seconds": 0.1, "t": 100.95, "step": 0},
            {"kind": "step", "rank": rank, "step": 1,
             "t0": 101.0, "t1": 102.0, "t": 102.0},
            {"kind": "compute", "rank": rank, "step": 1,
             "t0": 101.0, "t1": 101.92, "t": 101.92},
            {"kind": "latency", "rank": rank, "cid": cb, "op": "AllReduce",
             "seq": 2, "seconds": 0.3, "t": 101.9, "step": 1},
            {"kind": "latency", "rank": rank, "cid": cb, "op": "AllReduce",
             "seq": 2, "seconds": 0.1, "t": 103.0},
        ]
    return world


def write_logs(tmp_path, per_rank):
    for rank, records in per_rank.items():
        with open(tmp_path / f"events-rank{rank}.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return str(tmp_path)


def test_report_decomposition_and_routes():
    rep = overlap.build_report(synthetic_overlap_world())
    assert rep["schema"] == overlap.SCHEMA
    assert rep["ranks"] == 2
    assert len(rep["steps"]) == 2  # distinct steps, aggregated cross-rank
    assert rep["totals"]["steps"] == 4  # rank-steps
    assert rep["ok"] and rep["covered"]
    tot = rep["per_rank"]["0"]["totals"]
    assert tot["comm_overlapped_s"] == pytest.approx(0.5)
    assert tot["comm_exposed_s"] == pytest.approx(0.1)
    assert tot["overlap_ratio"] == pytest.approx(0.5 / 0.6)
    routes = {(r["op"], r["impl"]): r for r in rep["routes"]}
    ring = routes[("AllReduce", "pallas_ring")]
    hlo = routes[("AllReduce", "hlo")]
    assert ring["samples"] == 2 and hlo["samples"] == 6
    # the hidden sample is the during-compute bandwidth cohort, the
    # exposed/outside-step ones the standalone cohort
    assert ring["during_n"] == 2 and ring["standalone_n"] == 0
    assert hlo["during_n"] == 2 and hlo["standalone_n"] == 4
    assert hlo["gbps_during_p50"] is not None
    assert hlo["gbps_standalone_p50"] is not None
    assert ring["predicted_frac"] == pytest.approx(
        costmodel.overlappable_fraction("AllReduce", "pallas_ring")
    )


def test_report_is_record_order_invariant():
    base = overlap.build_report(synthetic_overlap_world())
    shuffled = synthetic_overlap_world()
    for rank in shuffled:
        random.Random(42 + rank).shuffle(shuffled[rank])
    assert overlap.build_report(shuffled) == base


def test_report_matches_golden():
    rep = json.loads(json.dumps(
        overlap.build_report(synthetic_overlap_world()), sort_keys=True
    ))
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert rep == golden, (
        "overlap report drifted from tests/data/overlap_golden.json — "
        "if intentional, regenerate with `python -m tests.test_overlap`"
    )


def test_format_report_and_exposed_render():
    rep = overlap.build_report(synthetic_overlap_world())
    txt = overlap.format_report(rep)
    assert "overlap" in txt and "exposed" in txt
    exp = overlap.format_exposed(rep)
    assert "exposed communication" in exp
    assert "AllReduce" in exp


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _run_cli(mod, *argv, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", mod, *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
    )


def test_cli_selftest():
    res = _run_cli("mpi4jax_tpu.observability.overlap", "--selftest")
    assert res.returncode == 0, res.stderr
    assert "overlap selftest: ok" in res.stdout


def test_cli_report_text_and_json(tmp_path):
    rundir = write_logs(tmp_path, synthetic_overlap_world())
    res = _run_cli("mpi4jax_tpu.observability.overlap", rundir)
    assert res.returncode == 0, res.stderr
    assert "exposed" in res.stdout
    res = _run_cli("mpi4jax_tpu.observability.overlap", rundir, "--json")
    assert res.returncode == 0, res.stderr
    rep = json.loads(res.stdout)
    assert rep["schema"] == overlap.SCHEMA and rep["ok"]


def test_doctor_perf_gains_exposed_section(tmp_path):
    rundir = write_logs(tmp_path, synthetic_overlap_world())
    res = _run_cli("mpi4jax_tpu.observability.doctor", "--perf", rundir)
    assert res.returncode == 0, res.stderr
    assert "exposed communication" in res.stdout


# ---------------------------------------------------------------------
# trace export: occupancy tracks (armed runs only)
# ---------------------------------------------------------------------


def test_trace_gains_occupancy_track_for_armed_runs():
    from mpi4jax_tpu.observability import trace

    obj = trace.build_trace(synthetic_overlap_world())
    names = [e for e in obj["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"]
    assert any(e["args"]["name"] == "steps" for e in names)
    slices = [e for e in obj["traceEvents"]
              if e.get("ph") == "X" and str(e.get("name", "")).startswith(
                  "step ")]
    assert len(slices) == 4  # 2 ranks x 2 steps
    assert all("comm_exposed" in e["args"] for e in slices)
    counters = [e for e in obj["traceEvents"]
                if e.get("ph") == "C" and e.get("name") == "occupancy (s)"]
    assert counters


def test_trace_without_steps_is_unchanged():
    from mpi4jax_tpu.observability import trace

    world = {
        rank: [r for r in recs if r["kind"] not in ("step", "compute")]
        for rank, recs in synthetic_overlap_world().items()
    }
    obj = trace.build_trace(world)
    assert not any(
        e.get("name") == "occupancy (s)" for e in obj["traceEvents"]
    )
    assert not any(
        e.get("ph") == "M" and e.get("args", {}).get("name") == "steps"
        for e in obj["traceEvents"]
    )


# ---------------------------------------------------------------------
# cost model: expected exposed time (the `lint --cost` column)
# ---------------------------------------------------------------------


def test_overlappable_fraction_by_impl():
    assert costmodel.overlappable_fraction("Isend") == 1.0
    assert costmodel.overlappable_fraction("Irecv") == 1.0
    assert costmodel.overlappable_fraction("AllReduce", "hlo") == 0.0
    assert costmodel.overlappable_fraction("AllReduce", "pallas_ring") \
        == 0.75
    assert costmodel.overlappable_fraction(
        "AllReduce", "algo:recursive_halving") == 0.5
    assert costmodel.overlappable_fraction("AllReduce") == 0.0


def test_expected_exposed_never_exceeds_expected():
    c = costmodel.record_cost(
        {"op": "AllReduce", "bytes": 1 << 20, "world": 4,
         "dtype": "float32"}
    )
    full = costmodel.expected_time_s(c)
    for impl in (None, "hlo", "pallas_ring", "algo:ring"):
        exp = costmodel.expected_exposed_s(c, impl=impl)
        assert 0.0 <= exp <= full + 1e-12
    # pipelined impls hide part of the wire time, monolithic ones none
    assert costmodel.expected_exposed_s(c, impl="pallas_ring") < full
    assert costmodel.expected_exposed_s(c, impl="hlo") \
        == pytest.approx(full)
    # fraction override wins over the impl default
    assert costmodel.expected_exposed_s(c, fraction=1.0) \
        == pytest.approx(0.0)
    assert costmodel.expected_exposed_s(c, fraction=0.0) \
        == pytest.approx(full)


def test_cost_report_carries_exposed_column():
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import trace_schedule
    from mpi4jax_tpu.analysis.schedule import cost_report
    from mpi4jax_tpu.analysis.schedule import format_cost_report

    def step(x):
        return m4t.allreduce(x)

    s = trace_schedule(step, (jnp.ones(8, jnp.float32),),
                       axis_env={"ranks": 4})
    rep = cost_report(s)
    for agg in rep["per_rank"].values():
        assert "exposed_s" in agg
        assert 0.0 <= agg["exposed_s"] <= agg["expected_s"] + 1e-12
    assert all("exposed_s" in g for g in rep["top"])
    assert "exposed" in format_cost_report(rep)


# ---------------------------------------------------------------------
# confirmed-straggler re-permutation loop (ROADMAP item 1 follow-on)
# ---------------------------------------------------------------------


def _verdict(rank, ratio=2.5):
    return {"kind": "verdict", "klass": "transient", "rank": rank,
            "t": 1.0, "finding": {"kind": "straggler", "rank": rank,
                                  "ratio": ratio, "op": "AllReduce"}}


def verdict_rundir(tmp_path, *, world=4, slow=((2, 3),), slow_beta=1.0,
                   ranks=(3,), ratio=2.5):
    """A run directory shaped like a live supervised run: a probed
    ``topology.json`` plus streaming-doctor verdicts in live.jsonl."""
    topo = topology.synthetic_map(topology.SyntheticLinkModel(
        world, beta_gbps=20.0,
        links={e: {"beta_gbps": slow_beta} for e in slow},
    ))
    topology.save(str(tmp_path / "topology.json"), topo)
    with open(tmp_path / "live.jsonl", "w") as f:
        for r in ranks:
            f.write(json.dumps(_verdict(r, ratio)) + "\n")
    return str(tmp_path)


def test_derive_from_verdicts_requires_a_map(tmp_path):
    with open(tmp_path / "live.jsonl", "w") as f:
        f.write(json.dumps(_verdict(3)) + "\n")
    doc, evidence = placement.derive_from_verdicts([str(tmp_path)])
    assert doc is None
    assert "no m4t-topo/1 map" in evidence["reason"]


def test_derive_from_verdicts_requires_verdicts(tmp_path):
    verdict_rundir(tmp_path, ranks=())
    doc, evidence = placement.derive_from_verdicts([str(tmp_path)])
    assert doc is None
    assert "no confirmed straggler" in evidence["reason"]


def test_derive_from_verdicts_rank_bound_declines(tmp_path):
    # uniform links: the straggler's links look like everyone else's
    rundir = verdict_rundir(tmp_path, slow=(), ranks=(3,))
    doc, evidence = placement.derive_from_verdicts([rundir])
    assert doc is None
    assert "rank-bound" in evidence["reason"]
    assert evidence["verdicts"] == 1 and not evidence["link_bound"]


def test_derive_from_verdicts_link_bound_proposes(tmp_path):
    rundir = verdict_rundir(tmp_path)
    doc, evidence = placement.derive_from_verdicts([rundir])
    assert doc is not None, evidence
    assert doc["perm"] != list(range(doc["world"]))
    ev = doc["verdict_evidence"]
    assert ev["verdicts"] == 1
    assert ev["link_bound_ranks"] == [3]
    assert ev["penalized_edges"] and all(
        p >= 2.5 for p in ev["penalized_edges"].values()
    )
    assert evidence["penalized_edges"] == ev["penalized_edges"]
    # the ordinary proof pipeline accepts the proposal
    proven = placement.prove(doc)
    assert proven["proof"]["verdict"] == "verified"


def test_placement_derive_cli_from_verdicts(tmp_path):
    rundir = verdict_rundir(tmp_path)
    out = str(tmp_path / "placement.json")
    res = _run_cli("mpi4jax_tpu.planner", "placement", "derive",
                   "--from-verdicts", rundir, "--json", "--out", out)
    assert res.returncode == 0, res.stderr
    payload = json.loads(res.stdout)
    assert payload["verified"] is True
    doc = payload["placement"]
    assert doc["verdict_evidence"]["link_bound_ranks"] == [3]
    assert "straggler verdict" in res.stderr
    saved = placement.load(out)
    assert saved["perm"] == doc["perm"]


def test_placement_derive_cli_needs_topo_or_verdicts():
    res = _run_cli("mpi4jax_tpu.planner", "placement", "derive")
    assert res.returncode == 2
    assert "--from-verdicts" in res.stderr


def test_launch_propose_placement_audits_supervisor(tmp_path, capsys):
    from mpi4jax_tpu import launch

    rundir = verdict_rundir(tmp_path)
    audit = os.path.join(rundir, "supervisor.jsonl")
    launch._propose_placement(rundir, audit)
    proposal = os.path.join(rundir, "placement-proposal.json")
    doc = placement.load(proposal)
    assert doc["proof"]["verdict"] == "verified"  # arrives proven
    (rec,) = [r for r in events.read(audit)
              if r.get("event") == "placement_proposal"]
    assert rec["perm"] == doc["perm"]
    assert rec["fingerprint"] == doc["fingerprint"]
    assert rec["evidence"]["link_bound_ranks"] == [3]
    assert rec["path"] == proposal
    assert "re-permutation" in capsys.readouterr().err


def test_launch_propose_placement_silent_without_evidence(tmp_path):
    from mpi4jax_tpu import launch

    # no topology map, no verdicts: must not create audit artifacts
    # (the --retries 0 backcompat contract: no supervisor.jsonl)
    with open(tmp_path / "events-rank0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "heartbeat", "rank": 0, "t": 1.0})
                + "\n")
    launch._propose_placement(
        str(tmp_path), os.path.join(str(tmp_path), "supervisor.jsonl")
    )
    assert not os.path.exists(tmp_path / "supervisor.jsonl")
    assert not os.path.exists(tmp_path / "placement-proposal.json")


# ---------------------------------------------------------------------
# launcher e2e (native toolchain): --overlap arming + slowdown shift
# ---------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


def _launch(tmp_path, n, script, *launch_args, timeout=240):
    path = str(tmp_path / "case.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n),
         *launch_args, path],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


def test_launch_overlap_requires_events_dir(tmp_path):
    res = _launch(tmp_path, 2, "pass", "--overlap")
    assert res.returncode == 2
    assert "--overlap requires --events-dir" in res.stderr


#: eager per-call collectives driven from a background thread while
#: the main thread owns the compute span: the comm tail past the
#: compute span is the *exposed* time the decomposition must name
OVERLAP_SCRIPT = """
import threading
import time

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu import observability as obs

x = jnp.ones(4096, jnp.float32)
jax.block_until_ready(m4t.allreduce(x, op=m4t.SUM))  # warmup


def comm_loop():
    for _ in range(12):
        jax.block_until_ready(m4t.allreduce(x, op=m4t.SUM))


for s in range(2):
    with obs.step_span(step=s):
        th = threading.Thread(target=comm_loop)
        with obs.compute_span():
            th.start()
            t_end = time.perf_counter() + 0.25
            while time.perf_counter() < t_end:
                sum(i * i for i in range(5000))
        th.join()
"""


@needs_native
def test_launch_overlap_slowdown_moves_comm_to_exposed(tmp_path):
    """Acceptance: in a 2-rank ``--overlap`` world the decomposition
    telescopes at full coverage, and an injected ``slowdown`` on every
    rank-0 AllReduce provably moves time from ``comm_overlapped`` to
    ``comm_exposed``."""
    base_dir = str(tmp_path / "base")
    res = _launch(tmp_path, 2, OVERLAP_SCRIPT,
                  "--events-dir", base_dir, "--overlap")
    assert res.returncode == 0, res.stderr
    assert "overlap attribution" in res.stderr  # the launcher's recap
    base = overlap.build_report(doctor.load([base_dir]))
    assert base["ranks"] == 2 and base["totals"]["steps"] == 4
    assert base["ok"], base["totals"]
    assert base["covered"]  # >= 90% of every step span is named

    slow_dir = str(tmp_path / "slow")
    res = _launch(
        tmp_path, 2, OVERLAP_SCRIPT,
        "--events-dir", slow_dir, "--overlap", "--fault-plan",
        '[{"rank": 0, "op": "AllReduce", "nth": 2, '
        '"action": "slowdown", "ms": 40}]',
    )
    assert res.returncode == 0, res.stderr
    slow = overlap.build_report(doctor.load([slow_dir]))
    assert slow["ok"], slow["totals"]
    # 11 slowed calls x 40ms per step dwarf the 0.25s compute window:
    # the comm tail lands after compute ends, i.e. exposed
    assert slow["totals"]["comm_exposed_s"] > \
        base["totals"]["comm_exposed_s"] + 0.1
    assert slow["totals"]["overlap_ratio"] < base["totals"]["overlap_ratio"]
    # unarmed control: same workload without --overlap writes no spans
    # and no step stamps (the byte-identical schema contract, e2e)
    plain_dir = str(tmp_path / "plain")
    res = _launch(tmp_path, 2, OVERLAP_SCRIPT, "--events-dir", plain_dir)
    assert res.returncode == 0, res.stderr
    recs = [r for rs in doctor.load([plain_dir]).values() for r in rs]
    assert not any(r["kind"] in ("step", "compute") for r in recs)
    assert not any("step" in r for r in recs)


if __name__ == "__main__":
    # regenerate the golden report after an intentional schema change
    rep = json.loads(json.dumps(
        overlap.build_report(synthetic_overlap_world()), sort_keys=True
    ))
    with open(GOLDEN, "w") as f:
        json.dump(rep, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"golden rewritten: {GOLDEN}")
