"""Sequence-parallel transformer training equivalence: ring- and
Ulysses-attention LMs trained over an sp-sharded mesh must match
single-device training step-for-step."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.models import attention as tfm
from mpi4jax_tpu.parallel import spmd

N = 8
T_LOCAL = 4
T = N * T_LOCAL

from tests.conftest import needs_size1_world



def make_cfg(attention, sp):
    return tfm.TransformerConfig(
        vocab=32,
        d_model=32,
        n_heads=8,
        n_layers=2,
        d_ff=64,
        sp_axis="ranks" if sp else None,
        sp_size=N if sp else 1,
        attention=attention,
    )


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
@needs_size1_world
def test_sp_training_matches_single_device(mesh, attention):
    cfg_sp = make_cfg(attention, sp=True)
    cfg_1 = make_cfg(attention, sp=False)

    key = jax.random.PRNGKey(0)
    params = tfm.init_params(cfg_1, key)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (T,), 0, cfg_1.vocab)
    targets = jnp.roll(tokens, -1)

    # single device
    p_ref, losses_ref = params, []
    step1 = jax.jit(lambda p: tfm.train_step(cfg_1, p, tokens, targets))
    for _ in range(2):
        p_ref, l = step1(p_ref)
        losses_ref.append(float(l))

    # sp-sharded: params replicated (stacked), tokens sharded
    stack = lambda a: jnp.broadcast_to(a, (N,) + a.shape)
    p_sp = jax.tree.map(stack, params)
    tok_sp = tokens.reshape(N, T_LOCAL)
    tgt_sp = targets.reshape(N, T_LOCAL)

    step_sp = spmd(
        lambda p, tk, tg: tfm.train_step(cfg_sp, p, tk, tg), mesh=mesh
    )
    losses_sp = []
    for _ in range(2):
        p_sp, l = step_sp(p_sp, tok_sp, tgt_sp)
        l = np.asarray(l)
        np.testing.assert_allclose(l, l[0], rtol=1e-5)  # replicated loss
        losses_sp.append(float(l[0]))

    np.testing.assert_allclose(losses_sp, losses_ref, rtol=2e-4)

    # params stay replicated and match the reference trajectory
    emb = np.asarray(jax.tree.leaves(p_sp)[0] if False else p_sp["embed"])
    np.testing.assert_allclose(emb[0], emb[3], rtol=1e-5)
    np.testing.assert_allclose(
        emb[0], np.asarray(p_ref["embed"]), rtol=2e-3, atol=1e-5
    )
