"""Shallow-water integration tests (analog of the reference's
``tests/test_examples.py`` which runs the demo for a model day and of
the implicit guarantee that domain decomposition does not change the
solution)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.models.shallow_water import (
    ModelState,
    ShallowWaterConfig,
    ShallowWaterModel,
)
from mpi4jax_tpu.parallel import spmd


def run_model(dims, n_steps, nx=48, ny=24, mesh=None):
    config = ShallowWaterConfig(nx=nx, ny=ny, dims=dims)
    model = ShallowWaterModel(config)
    blocks = model.initial_state_blocks()
    n = config.n_ranks
    if n == 1:
        state = ModelState(*(jnp.asarray(b[0]) for b in blocks))
        state = jax.jit(lambda s: model.step(s, first_step=True))(state)
        state = jax.jit(lambda s: model.multistep(s, n_steps))(state)
        h = np.asarray(state.h)[None]
    else:
        state = ModelState(*(jnp.asarray(b) for b in blocks))
        state = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
        state = spmd(lambda s: model.multistep(s, n_steps), mesh=mesh)(state)
        h = np.asarray(state.h)
    return model.reassemble(h, dims) if True else h


def test_single_rank_runs_and_stays_finite():
    h = run_model((1, 1), 20)
    assert np.all(np.isfinite(h))
    # the jet should still be near the resting depth
    assert 90 < h.mean() < 110


@pytest.mark.parametrize("dims", [(2, 4), (1, 8), (2, 1)])
def test_decomposition_invariance(mesh, dims):
    """The headline correctness property: the decomposed solve equals
    the single-rank solve (validates every halo-exchange path:
    periodic x wrap, closed y walls, interior exchanges)."""
    if dims[0] * dims[1] != 8 and dims != (2, 1):
        pytest.skip("mesh is 8-wide")
    n_steps = 12
    h_ref = run_model((1, 1), n_steps)
    if dims == (2, 1):
        from mpi4jax_tpu.parallel import world_mesh

        sub = world_mesh(2)
        h_dist = run_model(dims, n_steps, mesh=sub)
    else:
        h_dist = run_model(dims, n_steps, mesh=mesh)
    np.testing.assert_allclose(h_dist, h_ref, rtol=2e-4, atol=2e-4)


def test_energy_sanity():
    """Momentum/height fields evolve (the model is not frozen)."""
    config = ShallowWaterConfig(nx=48, ny=24, dims=(1, 1))
    model = ShallowWaterModel(config)
    blocks = model.initial_state_blocks()
    state = ModelState(*(jnp.asarray(b[0]) for b in blocks))
    s1 = jax.jit(lambda s: model.step(s, first_step=True))(state)
    s2 = jax.jit(lambda s: model.multistep(s, 10))(s1)
    assert not np.allclose(np.asarray(s1.h), np.asarray(s2.h))
