"""Pipeline parallelism: the GPipe schedule over the ring must equal
the sequential composition of all stages, forward and backward."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.parallel.pipeline import gpipe

N = 8
M = 4   # microbatches
B = 3   # microbatch size
D = 5

from tests.conftest import needs_size1_world



def stage_fn(params, h):
    w, b = params
    return jnp.tanh(h @ w + b)


@pytest.fixture()
def stage_weights():
    rng = np.random.RandomState(0)
    w = rng.randn(N, D, D).astype(np.float32) / np.sqrt(D)
    b = rng.randn(N, D).astype(np.float32) * 0.1
    return w, b


def sequential(w, b, x):
    h = x
    for s in range(N):
        h = np.tanh(h @ w[s] + b[s])
    return h


def test_gpipe_forward(run_spmd, stage_weights):
    w, b = stage_weights
    rng = np.random.RandomState(1)
    x = rng.randn(M, B, D).astype(np.float32)

    def f(wl, bl, mb):
        return gpipe(stage_fn, (wl, bl), mb)

    mb_stack = np.tile(x, (N, 1, 1, 1))
    out = run_spmd(f, jnp.asarray(w), jnp.asarray(b), jnp.asarray(mb_stack))

    expected = np.stack([sequential(w, b, x[i]) for i in range(M)])
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=2e-4, atol=1e-5)


def test_gpipe_backward(run_spmd, stage_weights):
    """jax.grad through the schedule = the backward pipeline; per-stage
    weight grads must match the sequential model's."""
    w, b = stage_weights
    rng = np.random.RandomState(2)
    x = rng.randn(M, B, D).astype(np.float32)

    def f(wl, bl, mb):
        def loss(wl_):
            out = gpipe(stage_fn, (wl_, bl), mb)
            return (out ** 2).sum()

        return jax.grad(loss)(wl)

    mb_stack = np.tile(x, (N, 1, 1, 1))
    grads = run_spmd(f, jnp.asarray(w), jnp.asarray(b), jnp.asarray(mb_stack))

    # sequential ground truth: grad w.r.t. each stage's weights
    def seq_loss(w_all):
        total = 0.0
        for i in range(M):
            h = jnp.asarray(x[i])
            for s in range(N):
                h = jnp.tanh(h @ w_all[s] + jnp.asarray(b[s]))
            total = total + (h ** 2).sum()
        return total

    g_ref = np.asarray(jax.grad(seq_loss)(jnp.asarray(w)))
    for r in range(N):
        np.testing.assert_allclose(grads[r], g_ref[r], rtol=2e-3, atol=1e-4)


def test_gpipe_many_microbatches_compiles_fast(run_spmd, stage_weights):
    """M=64 microbatches: the lax.scan schedule keeps the trace O(1) in
    M, so tracing + compiling stays in seconds (the unrolled schedule
    scaled linearly — round-1 VERDICT weak item 5). Grads still match
    the sequential oracle on a spot-check."""
    import time

    w, b = stage_weights
    m_big = 64
    rng = np.random.RandomState(3)
    x = rng.randn(m_big, B, D).astype(np.float32)

    def f(wl, bl, mb):
        out = gpipe(stage_fn, (wl, bl), mb)
        return jax.grad(
            lambda wl_: (gpipe(stage_fn, (wl_, bl), mb) ** 2).sum()
        )(wl), out

    t0 = time.perf_counter()
    mb_stack = np.tile(x, (N, 1, 1, 1))
    grads, out = run_spmd(f, jnp.asarray(w), jnp.asarray(b), jnp.asarray(mb_stack))
    elapsed = time.perf_counter() - t0
    assert elapsed < 60, f"M=64 pipeline took {elapsed:.1f}s — trace not O(1)?"

    expected = np.stack([sequential(w, b, x[i]) for i in range(m_big)])
    np.testing.assert_allclose(out[0], expected, rtol=2e-4, atol=1e-5)
    # the M=4 tests already check grads against the sequential oracle;
    # here just assert the M=64 backward pipeline produced usable grads
    assert np.isfinite(grads).all() and np.abs(grads).sum() > 0


@needs_size1_world
def test_gpipe_single_rank(stage_weights):
    w, b = stage_weights
    x = np.ones((M, B, D), np.float32)
    out = gpipe(stage_fn, (jnp.asarray(w[0]), jnp.asarray(b[0])), jnp.asarray(x))
    expected = np.tanh(x @ w[0] + b[0])
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)