"""Collective cost model, achieved-bandwidth attribution, anomaly
watch, and the bench regression gate
(``mpi4jax_tpu/observability/{costmodel,perf}.py``).

Covers the ISSUE-4 acceptance surface:

- golden table pinning expected wire bytes / steps for every op in the
  emit vocabulary x ring sizes {2,4,8} x {f32, bf16} (+ the quantized
  wire format), as literal numbers — the model is tested against the
  algorithm math, not against itself;
- the costmodel's quantized mirror pinned to the canonical helpers
  beside the kernel (``ops/quantized.py``);
- attribution: cid joins, op-level fallback, axes grouping, finite
  achieved bandwidth / %-of-peak;
- the EWMA+MAD anomaly watch: warmup, slow-only flagging,
  re-baselining, and the zero-overhead disabled path;
- BENCH_*.json history parsing (wrapper + bare schemas) and the gate:
  exit 0 on a copy of the repo's current trajectory, non-zero on a
  synthetically regressed copy;
- CLI smoke: ``--selftest`` (the tier-1 hook that keeps the CLI from
  rotting), ``report -o`` markdown, ``doctor --perf``;
- end-to-end: a real 2-rank ``launch --events-dir --perf`` run on CPU
  produces a finite per-op achieved-bandwidth table.
"""

import glob
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from mpi4jax_tpu.observability import costmodel, doctor, perf

pytestmark = [pytest.mark.telemetry, pytest.mark.perf]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# golden cost table: 1024-element payloads
# ---------------------------------------------------------------------

#: op -> {n -> (wire_bytes_f32, wire_bytes_bf16, steps)} for a
#: 1024-element payload (f32: 4096 B, bf16: 2048 B). Literal numbers,
#: derived by hand from the algorithm table in costmodel's docstring.
GOLDEN = {
    "AllReduce": {2: (4096, 2048, 2), 4: (6144, 3072, 6),
                  8: (7168, 3584, 14)},
    "ReduceScatter": {2: (2048, 1024, 1), 4: (3072, 1536, 3),
                      8: (3584, 1792, 7)},
    "AllGather": {2: (4096, 2048, 1), 4: (12288, 6144, 3),
                  8: (28672, 14336, 7)},
    "AllToAll": {2: (2048, 1024, 1), 4: (3072, 1536, 3),
                 8: (3584, 1792, 7)},
    "Bcast": {2: (4096, 2048, 1), 4: (4096, 2048, 2),
              8: (4096, 2048, 3)},
    "Reduce": {2: (4096, 2048, 1), 4: (4096, 2048, 2),
               8: (4096, 2048, 3)},
    "Gather": {2: (4096, 2048, 1), 4: (12288, 6144, 3),
               8: (28672, 14336, 7)},
    "Scatter": {2: (4096, 2048, 1), 4: (12288, 6144, 3),
                8: (28672, 14336, 7)},
    "Scan": {2: (4096, 2048, 1), 4: (4096, 2048, 3),
             8: (4096, 2048, 7)},
    "Barrier": {2: (0, 0, 1), 4: (0, 0, 2), 8: (0, 0, 3)},
    "Send": {2: (4096, 2048, 1), 4: (4096, 2048, 1),
             8: (4096, 2048, 1)},
    "Recv": {2: (4096, 2048, 1), 4: (4096, 2048, 1),
             8: (4096, 2048, 1)},
    "Sendrecv": {2: (4096, 2048, 1), 4: (4096, 2048, 1),
                 8: (4096, 2048, 1)},
}

#: quantized: wire format is int8 + one f32 scale per 256-value block,
#: per hop on a block-aligned per-rank chunk; 2(n-1) hops. For 1024
#: elements: chunks 512/256/256 -> hops 520/260/260 bytes.
GOLDEN_QUANTIZED = {2: (1040, 2), 4: (1560, 6), 8: (3640, 14)}


@pytest.mark.parametrize("op", sorted(GOLDEN))
@pytest.mark.parametrize("n", [2, 4, 8])
def test_golden_wire_bytes_and_steps(op, n):
    wire_f32, wire_bf16, steps = GOLDEN[op][n]
    c32 = costmodel.cost(op, nbytes=4096, world=n, dtype="float32")
    assert (c32["wire_bytes"], c32["steps"]) == (wire_f32, steps)
    c16 = costmodel.cost(op, nbytes=2048, world=n, dtype="bfloat16")
    assert (c16["wire_bytes"], c16["steps"]) == (wire_bf16, steps)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_golden_quantized_wire_bytes(n):
    wire, steps = GOLDEN_QUANTIZED[n]
    # 1024 f32 elements on the wire as int8 + block scales: the same
    # element count regardless of the input dtype's width
    c = costmodel.cost("QuantizedAllReduce", nbytes=4096, world=n,
                       dtype="float32")
    assert (c["wire_bytes"], c["steps"]) == (wire, steps)
    c16 = costmodel.cost("QuantizedAllReduce", nbytes=2048, world=n,
                         dtype="bfloat16")
    assert (c16["wire_bytes"], c16["steps"]) == (wire, steps)


def test_world_one_and_unknown_ops():
    for op in list(GOLDEN) + ["QuantizedAllReduce"]:
        c = costmodel.cost(op, nbytes=4096, world=1, dtype="float32")
        assert c["wire_bytes"] == 0 and c["steps"] == 0
    c = costmodel.cost("FrobnicateAll", nbytes=100, world=4)
    assert c["algorithm"] == "unknown" and c["wire_bytes"] == 100


def test_quantized_mirror_matches_kernel():
    """The costmodel's import-light mirror of the quantized wire
    format must agree with the canonical helpers that live beside the
    kernel — this is the drift pin."""
    quantized = pytest.importorskip("mpi4jax_tpu.ops.quantized")
    for elems in (1, 255, 256, 257, 1024, 5000, 65536):
        assert costmodel._quant_wire_format_bytes(elems) == (
            quantized.wire_format_bytes(elems)
        )
        for n in (2, 3, 4, 8):
            assert costmodel._quant_ring_chunk_elems(elems, n) == (
                quantized.ring_chunk_elems(elems, n)
            )


def test_expected_time_alpha_beta():
    c = costmodel.cost("AllReduce", nbytes=4096, world=2)
    t = costmodel.expected_time_s(c, gbps=1.0, alpha=1e-6)
    assert t == pytest.approx(2 * 1e-6 + 4096 / 1e9)
    assert costmodel.achieved_gbps(c, 4096e-9) == pytest.approx(1.0)
    assert costmodel.achieved_gbps(c, 0.0) is None


def test_peak_gbps_resolution(monkeypatch):
    monkeypatch.setenv("M4T_PEAK_GBPS", "123.5")
    assert costmodel.peak_gbps() == 123.5
    monkeypatch.delenv("M4T_PEAK_GBPS")
    assert costmodel.peak_gbps("TPU v5 lite") == 200.0
    assert costmodel.peak_gbps("TPU v4") == 300.0
    assert costmodel.peak_gbps("cpu") == costmodel.DEFAULT_PEAK_GBPS
    assert costmodel.peak_gbps() == costmodel.DEFAULT_PEAK_GBPS


# ---------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------


def _emission(rank, seq, op, *, nbytes=4096, world=2, cid=None,
              axes=("ranks",), dtype="float32"):
    return {"kind": "emission", "rank": rank, "seq": seq, "op": op,
            "bytes": nbytes, "dtype": dtype, "axes": list(axes),
            "world": world, "cid": cid or f"c{rank}{seq}", "t": 100.0 + seq}


def _latency(rank, op, seconds, *, cid=None, seq=None):
    return {"kind": "latency", "rank": rank, "op": op, "cid": cid,
            "seq": seq, "seconds": seconds, "t": 101.0}


def test_attribute_joins_by_cid_and_groups_by_fingerprint():
    by_rank = {
        0: [
            _emission(0, 1, "AllReduce", cid="a"),
            _emission(0, 2, "AllReduce", nbytes=8192, cid="b"),
            _latency(0, "AllReduce", 0.001, cid="a"),
            _latency(0, "AllReduce", 0.002, cid="b"),
        ],
        1: [
            _emission(1, 1, "AllReduce", cid="c"),
            _emission(1, 2, "AllReduce", nbytes=8192, cid="d"),
            _latency(1, "AllReduce", 0.003, cid="c"),
        ],
    }
    result = perf.attribute(by_rank, peak=100.0)
    rows = {(r["bytes"]): r for r in result["rows"]}
    assert set(rows) == {4096, 8192}
    small, big = rows[4096], rows[8192]
    assert small["emissions"] == 2 and small["samples"] == 2
    assert big["emissions"] == 2 and big["samples"] == 1
    assert small["wire_bytes"] == 4096 and big["wire_bytes"] == 8192
    # p50 of [0.001, 0.003] = 0.002 -> 4096B / 2ms
    assert small["lat_p50_s"] == pytest.approx(0.002)
    assert small["achieved_gbps"] == pytest.approx(4096 / 0.002 / 1e9)
    assert small["pct_of_peak"] == pytest.approx(
        100 * small["achieved_gbps"] / 100.0
    )
    assert small["slowdown"] > 1


def test_attribute_op_level_fallback_for_unjoined_latency():
    # latency with no cid attaches to the dominant fingerprint group
    by_rank = {0: [
        _emission(0, 1, "AllGather"),
        _emission(0, 2, "AllGather"),
        _latency(0, "AllGather", 0.004),
    ]}
    (row,) = perf.attribute(by_rank)["rows"]
    assert row["samples"] == 1 and row["lat_p50_s"] == pytest.approx(0.004)


def test_attribute_without_samples_still_models():
    (row,) = perf.attribute({0: [_emission(0, 1, "Bcast")]})["rows"]
    assert row["wire_bytes"] == 4096 and "lat_p50_s" not in row
    text = perf.format_table(perf.attribute({0: [_emission(0, 1, "Bcast")]}))
    assert "Bcast" in text and "%peak" in text


def test_perf_report_live_registry():
    from mpi4jax_tpu import observability as obs

    obs.enable()
    obs.reset()
    try:
        obs.registry.record_emission(
            "AllReduce", nbytes=1 << 20, dtype="float32",
            axes=["ranks"], world=8, cid="liv1",
        )
        obs.registry.record_latency("AllReduce", 0.010)
        text = obs.perf_report()
    finally:
        obs.reset()
        obs.disable()
    assert "AllReduce" in text
    # 2*(7/8)*1MiB over 10ms, finite and positive
    assert "GB/s" in text and "-" not in text.splitlines()[-1].split()[-3]


# ---------------------------------------------------------------------
# anomaly watch
# ---------------------------------------------------------------------


def test_watch_warmup_then_flags_slow_only():
    watch = perf.PerfWatch(z=6.0, warmup=5, emit=False)
    jitter = [1.00, 1.02, 0.98, 1.01, 0.99]
    for i in range(30):
        assert watch.observe("k", 0.001 * jitter[i % 5]) is None
    # a fast outlier never flags
    assert watch.observe("k", 1e-6) is None
    anomaly = watch.observe("k", 0.1)
    assert anomaly is not None and anomaly["z"] >= 6.0
    assert anomaly["seconds"] == 0.1 and anomaly["kind"] == "anomaly"
    assert watch.anomalies[-1] is anomaly


def test_watch_rebaselines_after_step_change():
    watch = perf.PerfWatch(z=6.0, warmup=3, smoothing=0.5, emit=False)
    for _ in range(10):
        watch.observe("k", 0.001)
    assert watch.observe("k", 0.1) is not None
    # the new level keeps feeding the baseline: it stops being an
    # anomaly instead of alarming forever
    flagged = [watch.observe("k", 0.1) is not None for _ in range(10)]
    assert not flagged[-1]


def test_watch_anomaly_emitted_to_sink(tmp_path):
    from mpi4jax_tpu.observability import events

    sink = str(tmp_path / "anomalies.jsonl")
    prev = events.get_sink()
    events.set_sink(sink)
    try:
        watch = perf.PerfWatch(z=6.0, warmup=3, emit=True)
        for _ in range(10):
            watch.observe("AllReduce[8:f32]@ranks", 0.001)
        assert watch.observe("AllReduce[8:f32]@ranks", 0.5, op="AllReduce")
    finally:
        events.set_sink(prev.path if prev else None)
    (rec,) = [r for r in events.read(sink) if r["kind"] == "anomaly"]
    assert rec["key"] == "AllReduce[8:f32]@ranks"
    assert rec["op"] == "AllReduce" and rec["z"] >= 6.0


def test_observe_runtime_disabled_is_inert():
    """Zero-overhead disabled path: without M4T_PERF_WATCH the runtime
    hook does nothing and allocates nothing."""
    assert not perf.watch_enabled()
    assert perf.observe_runtime("AllReduce", 0.001) is None
    assert perf.get_watch() is None


def test_observe_runtime_enabled_keys_by_fingerprint():
    watch = perf.enable_watch(z=6.0, warmup=3, emit=False)
    try:
        rec = {"op": "AllReduce", "bytes": 4096, "dtype": "float32",
               "shape": [1024], "axes": ["ranks"], "world": 2, "seq": 7}
        for _ in range(10):
            assert perf.observe_runtime(
                "AllReduce", 0.001, record=rec, cid="x"
            ) is None
        anomaly = perf.observe_runtime("AllReduce", 0.5, record=rec, cid="x")
        assert anomaly is not None
        assert anomaly["key"] == "AllReduce[1024:float32]@ranks"
        assert anomaly["world"] == 2 and anomaly["seq"] == 7
    finally:
        perf.disable_watch()
        watch.reset()


# ---------------------------------------------------------------------
# bench history + gate
# ---------------------------------------------------------------------


def _write_round(directory, n, value, *, rc=0, vs_baseline=None, nproc=1,
                 variant=""):
    name = f"BENCH_r{n:02d}{'_' + variant if variant else ''}.json"
    with open(os.path.join(directory, name), "w") as f:
        json.dump({
            "n": n, "cmd": "if [ -f bench.py ]; then python bench.py; fi",
            "rc": rc, "tail": "...",
            "parsed": {"metric": "shallow_water_100x_solve", "value": value,
                       "unit": "s", "vs_baseline": vs_baseline,
                       "nproc": nproc},
        }, f)


def test_history_parses_wrapper_and_bare_schemas(tmp_path):
    _write_round(tmp_path, 1, 100.0)
    # bare record (the BENCH_rNN_tpu.json shape tpu_watch writes)
    with open(tmp_path / "BENCH_r02_tpu.json", "w") as f:
        json.dump({"metric": "m", "value": 0.5, "unit": "s",
                   "vs_baseline": 12.0, "nproc": 1}, f)
    main = perf.load_history(str(tmp_path))
    assert [r["round"] for r in main] == [1]
    assert main[0]["value"] == 100.0 and main[0]["rc"] == 0
    tpu = perf.load_history(str(tmp_path), variant="tpu")
    assert [r["round"] for r in tpu] == [2]
    assert tpu[0]["vs_baseline"] == 12.0


def test_gate_passes_on_copy_of_repo_trajectory(tmp_path):
    """Acceptance: gate exits 0 on the repo's current BENCH_*.json
    trajectory (tested on a copy so the test stays hermetic)."""
    files = glob.glob(os.path.join(REPO, "BENCH_r*.json"))
    assert files, "repo lost its BENCH trajectory?"
    for path in files:
        shutil.copy(path, tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "gate", "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "gate: ok" in res.stdout or "insufficient_history" in res.stdout


def test_gate_fails_on_synthetically_regressed_copy(tmp_path):
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        shutil.copy(path, tmp_path)
    _write_round(tmp_path, 97, 10_000.0)  # the regression
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "gate", "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "regressed" in res.stdout


def test_gate_cohorts_and_verdicts(tmp_path):
    # improving trajectory passes
    for n, v in ((1, 100.0), (2, 90.0), (3, 88.0)):
        _write_round(tmp_path, n, v)
    assert perf.gate_history(perf.load_history(str(tmp_path)))["ok"]
    # an on-chip round does not gate against CPU rounds
    _write_round(tmp_path, 4, 0.5, vs_baseline=12.0)
    verdict = perf.gate_history(perf.load_history(str(tmp_path)))
    assert verdict["verdict"] == "insufficient_history" and verdict["ok"]
    # within the noise band is ok; beyond it fails
    _write_round(tmp_path, 5, 95.0)
    assert perf.gate_history(perf.load_history(str(tmp_path)))["ok"]
    _write_round(tmp_path, 6, 200.0)
    verdict = perf.gate_history(perf.load_history(str(tmp_path)))
    assert verdict["verdict"] == "regressed" and not verdict["ok"]
    # a failed latest run fails regardless of its value
    _write_round(tmp_path, 7, 1.0, rc=2)
    verdict = perf.gate_history(perf.load_history(str(tmp_path)))
    assert verdict["verdict"] == "latest_run_failed" and not verdict["ok"]


def test_gate_no_history_exit_2(tmp_path):
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "gate", "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 2


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _write_run_dir(tmp_path):
    rundir = tmp_path / "run"
    rundir.mkdir()
    for rank in (0, 1):
        with open(rundir / f"events-rank{rank}.jsonl", "w") as f:
            for seq in (1, 2, 3):
                cid = f"c{rank}{seq}"
                f.write(json.dumps(_emission(rank, seq, "AllReduce",
                                             cid=cid)) + "\n")
                f.write(json.dumps(_latency(rank, "AllReduce",
                                            0.001 * seq, cid=cid)) + "\n")
    return str(rundir)


def test_cli_selftest():
    """The tier-1 hook: the CLI's device-free smoke must keep passing
    (synthetic events, markdown, both gate verdicts, the watch)."""
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "--selftest"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "perf selftest ok" in res.stdout


def test_cli_report_writes_markdown(tmp_path):
    rundir = _write_run_dir(tmp_path)
    md = str(tmp_path / "PERF_REPORT.md")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "report", rundir, "-o", md, "--peak-gbps", "50"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "AllReduce" in res.stdout
    content = open(md).read()
    assert "# Performance report" in content
    assert "ring reduce-scatter + all-gather" in content


def test_cli_report_json_finite(tmp_path):
    rundir = _write_run_dir(tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "report", rundir, "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    (row,) = json.loads(res.stdout)["rows"]
    assert row["samples"] == 6
    assert row["achieved_gbps"] > 0 and row["pct_of_peak"] > 0


def test_cli_report_no_input_exit_2(tmp_path):
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "report", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 2


def test_cli_compare_event_dirs(tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    for d, scale in ((a, 1.0), (b, 10.0)):  # b is 10x slower
        d.mkdir()
        with open(d / "events-rank0.jsonl", "w") as f:
            for seq in (1, 2, 3, 4):
                cid = f"c{seq}"
                f.write(json.dumps(_emission(0, seq, "AllReduce",
                                             cid=cid)) + "\n")
                f.write(json.dumps(_latency(0, "AllReduce", 0.001 * scale,
                                            cid=cid)) + "\n")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.perf",
         "compare", str(a), str(b)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSED" in res.stdout


def test_doctor_perf_section(tmp_path):
    rundir = _write_run_dir(tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.observability.doctor",
         rundir, "--perf"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "no findings" in res.stdout
    assert "perf attribution vs peak" in res.stdout
    assert "AllReduce" in res.stdout


# ---------------------------------------------------------------------
# end-to-end: real 2-rank launch --events-dir --perf on CPU
# ---------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


@needs_native
def test_launch_perf_roundtrip(tmp_path):
    """Acceptance: ``launch --events-dir --perf`` on the CPU container
    -> per-rank latency events -> a finite per-op achieved-bandwidth
    table from both the launcher's inline section and the offline
    ``perf report``."""
    script = tmp_path / "case.py"
    with open(script, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(
            """
            import jax.numpy as jnp
            import mpi4jax_tpu as m4t
            from mpi4jax_tpu.runtime import shm
            x = jnp.arange(1024.0) + shm.rank()
            for _ in range(4):
                x = m4t.allreduce(x)
            print(f"OK{shm.rank()}")
            """
        ))
    rundir = str(tmp_path / "run")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--events-dir", rundir, "--perf", str(script)],
        env=env, capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    assert "OK0" in res.stdout and "OK1" in res.stdout
    # the launcher printed its inline attribution section
    assert "perf attribution" in res.stderr
    assert "AllReduce" in res.stderr
    # offline round trip over the same artifacts
    by_rank = doctor.load([rundir])
    assert sorted(by_rank) == [0, 1]
    result = perf.attribute(by_rank)
    (row,) = [r for r in result["rows"] if r["op"] == "AllReduce"]
    assert row["emissions"] == 8  # 4 collectives x 2 ranks
    assert row["samples"] >= 1
    for field in ("lat_p50_s", "achieved_gbps", "pct_of_peak"):
        value = row[field]
        assert isinstance(value, float) and value > 0, (field, value)
    assert row["wire_bytes"] == 4096  # 2*(n-1)/n * 4KiB at n=2
