"""Smoke tests for the evidence scripts in ``benchmarks/``: each must
run end-to-end on a forced-CPU platform at reduced scale and emit its
JSON artifact with the agreed schema. This pins the plumbing (artifact
names, field names, subprocess isolation) by CI *before* a chip window
— the scripts' real numbers can only be captured when the TPU tunnel
answers, and a window that hits a schema bug is a window lost
(VERDICT r4 next #5).
"""

import json
import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks")

#: under the launcher-world CI legs every rank runs this file
#: concurrently; a per-rank round number keeps the scripts' fixed
#: artifact paths from racing (both ranks writing + unlinking the
#: same results_r99_*.json)
SCRATCH_ROUND = str(90 + int(os.environ.get("M4T_RANK", "9")))


def run_script(script, env_extra, timeout=560):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["M4T_ROUND"] = SCRATCH_ROUND
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(BENCH, script)],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


def last_json_line(stdout):
    line = [ln for ln in stdout.splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def test_dispatch_micro_cpu(tmp_path):
    res = run_script(
        "dispatch_micro.py",
        {"M4T_DISPATCH_PLATFORM": "cpu", "M4T_DISPATCH_ITERS": "3",
         },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    artifact = last_json_line(res.stdout)["artifact"]
    assert artifact.endswith(
        f"results_r{SCRATCH_ROUND}_dispatch_micro.json"
    )
    with open(artifact) as f:
        data = json.load(f)
    os.unlink(artifact)
    assert data["platform"] == "cpu"
    assert "tunnel_roundtrip_ms" in data and "noop_jit_ms" in data
    for op in ("allreduce", "allgather", "alltoall", "sendrecv", "bcast"):
        row = data["ops"][op]
        assert {"eager_ms_per_call", "jit_ms_per_call",
                "chained_us_per_op"} <= set(row)


def test_fullspan_equiv_cpu():
    res = run_script(
        "fullspan_equiv.py",
        {"M4T_EQUIV_PLATFORM": "cpu", "M4T_EQUIV_SCALE": "1",
         },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    artifact = last_json_line(res.stdout)["artifact"]
    with open(artifact) as f:
        data = json.load(f)
    os.unlink(artifact)
    assert data["platform"] == "cpu"
    assert data["num_steps"] > 400
    # on CPU the fused paths must be recorded as errors (Mosaic is
    # TPU-only), never silently dropped
    for spp in (1, 2):
        assert f"fused_spp{spp}" in data["paths"]
        assert "error" in data["paths"][f"fused_spp{spp}"]


def test_fullspan_equiv_calibration_cpu():
    """The f64-vs-f32 calibration leg writes its own artifact (so an
    on-chip capture can't clobber the yardstick) and records a
    nonzero noise amplification."""
    res = run_script(
        "fullspan_equiv.py",
        {"M4T_EQUIV_PLATFORM": "cpu", "M4T_EQUIV_SCALE": "1",
         "M4T_EQUIV_CALIBRATE": "1", },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    artifact = last_json_line(res.stdout)["artifact"]
    assert artifact.endswith(
        f"results_r{SCRATCH_ROUND}_fullspan_equiv_calib.json"
    )
    with open(artifact) as f:
        data = json.load(f)
    os.unlink(artifact)
    calib = data["calibration_f64_vs_f32"]
    assert 0.0 < calib["worst_scaled_dev"] < 1e-2


def test_roofline_cpu_plumbing():
    env = {
        "M4T_ROOFLINE_PLATFORM": "cpu",
        "M4T_ROOFLINE_SCALE": "10",  # benchmark width: fence visible
        "M4T_ROOFLINE_STEPS": "5",
        "M4T_ROOFLINE_REPEATS": "1",
        "M4T_ROOFLINE_ROW_TIMEOUT": "120",
        # plumbing test: one timed row + the fence rows is enough
        "M4T_ROOFLINE_ONLY": "xla_step",
    }
    res = run_script("roofline.py", env)
    assert res.returncode == 0, res.stderr[-2000:]
    artifact = last_json_line(res.stdout)["artifact"]
    with open(artifact) as f:
        data = json.load(f)
    os.unlink(artifact)
    assert data["platform"] == "cpu"
    rows = {r["config"]: r for r in data["rows"]}
    assert rows["xla_step"]["ms_per_step"] > 0
    # the r4 failure sizes are fenced, not attempted — for every
    # temporal-blocking depth (the deeper halo only shrinks the fence)
    for b in (200, 240, 320):
        assert "fenced" in rows[f"fused_b{b}"]
        assert "fenced" in rows[f"fused2_b{b}"]
        assert "fenced" in rows[f"fused4_b{b}"]
    # the headline size stays compilable at the empirically verified
    # depth (spp=1: block 160 compiled and ran on v5e)
    assert "fenced" not in rows.get("fused_b160", {})
    # deeper variants are charged for their unrolled intermediates
    # (fused_step.vmem_model_bytes steps_per_pass term, ADVICE.md):
    # b160 exceeds the ceiling at depth >= 2, so those rows must be
    # fenced rather than submitted as the unmodeled compile class
    # suspected of wedging the r4 session ...
    assert "fenced" in rows["fused2_b160"]
    assert "fenced" in rows["fused4_b160"]
    # ... while every depth keeps a compilable rung to fall back to
    assert "fenced" not in rows.get("fused2_b128", {})
    assert "fenced" not in rows.get("fused4_b80", {})


def test_mosaic_diag_cpu():
    res = run_script(
        "mosaic_diag.py",
        {"M4T_DIAG_PLATFORM": "cpu", "M4T_DIAG_TIMEOUT": "120",
         },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    artifact = last_json_line(res.stdout)["artifact"]
    with open(artifact) as f:
        data = json.load(f)
    os.unlink(artifact)
    attempts = {a["block_rows"]: a for a in data["attempts"]}
    assert set(attempts) == {200, 240, 320}
    # CPU cannot compile Mosaic: every attempt records a captured
    # failure with the error tail preserved
    for rec in attempts.values():
        assert rec["outcome"] == "failed"
        assert rec["tail"]
