"""True-width float64 / complex128 coverage of the XLA collective path.

The main suite runs with ``jax_enable_x64=False`` (``conftest.py``), so
its "float64" parametrizations silently execute at f32. The reference
tests genuine f64/c128 on every op (``_src/utils.py:101-128`` dtype
table + per-op tests); this module closes that gap by running the op
sweep in a subprocess with ``jax_enable_x64=True`` (the flag must be
set before the backend initializes, hence the subprocess) and asserting
both the output dtype and precision that only survives at 64-bit width
(offsets of 1e-12 are representable in f64, absorbed at f32).
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.parallel import spmd

N = 8
EPS = 1e-12  # representable at f64, absorbed at f32

# --- allreduce f64: precision must survive ---------------------------------
base = np.full((N, 4), 1.0, np.float64)
arr = base + EPS * (np.arange(N, dtype=np.float64)[:, None] + 1)
out = spmd(lambda x: m4t.allreduce(x, op=m4t.SUM))(jnp.asarray(arr))
assert out.dtype == jnp.float64, out.dtype
expected = arr.sum(axis=0)
np.testing.assert_allclose(np.asarray(out)[0], expected, rtol=0, atol=1e-15)
# the f64-only part of the signal must be present
assert abs(np.asarray(out)[0, 0] - N) > 30 * EPS

# --- allreduce c128 --------------------------------------------------------
carr = (arr + 1j * (2 * arr)).astype(np.complex128)
out = spmd(lambda x: m4t.allreduce(x, op=m4t.SUM))(jnp.asarray(carr))
assert out.dtype == jnp.complex128, out.dtype
np.testing.assert_allclose(np.asarray(out)[0], carr.sum(axis=0), atol=1e-15)

# --- i64: values above the i32 range --------------------------------------
ia = np.full((N, 3), (1 << 40), np.int64) + np.arange(N, dtype=np.int64)[:, None]
out = spmd(lambda x: m4t.allreduce(x, op=m4t.SUM))(jnp.asarray(ia))
assert out.dtype == jnp.int64, out.dtype
np.testing.assert_array_equal(np.asarray(out)[0], ia.sum(axis=0))

# --- allgather / alltoall c128 --------------------------------------------
xg = (np.arange(N, dtype=np.float64)[:, None] + EPS + 1j).astype(np.complex128)
out = spmd(m4t.allgather)(jnp.asarray(xg))
assert out.dtype == jnp.complex128
np.testing.assert_allclose(np.asarray(out)[0], xg, atol=0)

xa = np.arange(N * N, dtype=np.float64).reshape(N, N, 1) * EPS
out = spmd(m4t.alltoall)(jnp.asarray(xa))
assert out.dtype == jnp.float64
np.testing.assert_allclose(
    np.asarray(out)[:, :, 0].T, xa[:, :, 0], rtol=0, atol=0
)

# --- bcast / gather / scatter / reduce / scan f64 --------------------------
xb = np.full((N, 2), np.pi, np.float64) + EPS * np.arange(N)[:, None]
out = spmd(lambda x: m4t.bcast(x, 0))(jnp.asarray(xb))
assert out.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(out)[3], xb[0], rtol=0, atol=0)

out = spmd(lambda x: m4t.gather(x, 0))(jnp.asarray(xb))
assert out.dtype == jnp.float64

blocks = np.arange(N * N, dtype=np.float64).reshape(N, N, 1) + EPS
out = spmd(lambda x: m4t.scatter(x, 0))(jnp.asarray(np.broadcast_to(blocks[0], (N, N, 1))))
assert out.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(out)[2, 0], blocks[0, 2, 0], rtol=0)

out = spmd(lambda x: m4t.reduce(x, op=m4t.SUM, root=0))(jnp.asarray(xb))
assert out.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(out)[0], xb.sum(axis=0), atol=1e-15)

out = spmd(lambda x: m4t.scan(x, op=m4t.SUM))(jnp.asarray(xb))
assert out.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(out)[5], xb[:6].sum(axis=0), atol=1e-15)

# --- sendrecv c128 ring ----------------------------------------------------
ring_dst = tuple((r + 1) % N for r in range(N))
ring_src = tuple((r - 1) % N for r in range(N))
xs = (np.arange(N, dtype=np.float64)[:, None] * EPS + 1j * np.ones((N, 2))).astype(
    np.complex128
)
out = spmd(
    lambda x: m4t.sendrecv(x, x, ring_src, ring_dst)
)(jnp.asarray(xs))
assert out.dtype == jnp.complex128
np.testing.assert_allclose(np.asarray(out)[3], xs[2], rtol=0, atol=0)

# --- send/recv f64 ---------------------------------------------------------
def sr(x):
    m4t.send(x, ring_dst, tag=4)
    return m4t.recv(x, ring_src, tag=4)

out = spmd(sr)(jnp.asarray(xb))
assert out.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(out)[3], xb[2], rtol=0, atol=0)

# --- grad through allreduce at f64 ----------------------------------------
g = spmd(lambda x: jax.grad(lambda v: m4t.allreduce(v, op=m4t.SUM).sum())(x))(
    jnp.asarray(xb)
)
assert g.dtype == jnp.float64
np.testing.assert_allclose(np.asarray(g), 1.0, rtol=0, atol=0)

print("X64_SWEEP_OK")
"""


def test_x64_op_sweep():
    path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"m4t_x64_{os.getpid()}.py"
    )
    with open(path, "w") as f:
        f.write(textwrap.dedent(_SCRIPT.format(repo=REPO)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    try:
        res = subprocess.run(
            [sys.executable, path],
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
            cwd=REPO,
        )
    finally:
        os.remove(path)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "X64_SWEEP_OK" in res.stdout
