"""PR 18: topology-aware placement + proof-gated schedule-space search.

Covers the acceptance surface:

- ``planner/placement.py``: the m4t-place/1 lifecycle — derivation
  beats identity on the adversarial fabric, M4T206 admission
  (``analysis/placement_check.py``), fingerprint/proof drift, atomic
  persistence with tamper detection, env arming;
- the 1000-seed schedule-isomorphism property: a verified permutation
  never changes any rank's schedule fingerprint sequence;
- ``launch --place``: simulator-verified-only — an unproven, stale,
  or world-mismatched permutation is BLOCKED before any rank spawns
  (witness on stderr), a proven one arms ``M4T_PLACEMENT`` into every
  rank end to end;
- transparent application: ``comm.CartComm`` grid embedding and
  ``parallel.mesh.world_mesh`` device reorder;
- ``planner/algogen.py``: the generator emits candidates that pass
  the full M4T201/202/204/205 proof pipeline, beat the shipped ring
  under ``costmodel.expected_time_topo`` on the adversarial fabric,
  register through the PR 15 registry unchanged, and are swept by
  ``planner tune`` on equal footing (the registry still refuses an
  unproven generated file);
- plan-cache provenance: the optional ``placement`` field round-trips
  and plans without one keep their pre-placement ``plan_id``;
- rule-catalog pins: M4T206 in ``analysis --rules`` and SARIF.
"""

import json
import os
import random
import subprocess
import sys

import pytest

from mpi4jax_tpu.analysis import placement_check
from mpi4jax_tpu.observability import topology
from mpi4jax_tpu.planner import algo as algomod
from mpi4jax_tpu.planner import algogen
from mpi4jax_tpu.planner import placement as placemod
from mpi4jax_tpu.planner import plan as planmod

pytestmark = [pytest.mark.tuning, pytest.mark.placement]

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORLD = 8


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("M4T_ALGO_PATH", None)
    env.pop("M4T_PLACEMENT", None)
    env.pop("M4T_PLAN_CACHE", None)
    return env


def _planner(*argv, timeout=240, env=None):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", *argv],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env or _clean_env(),
    )


def _launch(*argv, timeout=240, env=None):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", *argv],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env or _clean_env(),
    )


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    # placement must never leak between tests (armed() reads the env
    # lazily), and the algo registry cache must not carry a previous
    # test's M4T_ALGO_PATH view
    monkeypatch.delenv(placemod.ENV_VAR, raising=False)
    monkeypatch.delenv("M4T_ALGO_PATH", raising=False)
    algomod.invalidate_cache()
    yield
    algomod.invalidate_cache()


@pytest.fixture(scope="module")
def adversarial():
    """The PR 18 acceptance fabric: a fast Hamiltonian cycle hidden
    among slow links, hostile to the identity ring."""
    return placemod.adversarial_topo(WORLD)


@pytest.fixture(scope="module")
def derived(adversarial):
    return placemod.derive(adversarial)


@pytest.fixture(scope="module")
def proven(derived):
    return placemod.prove(derived)


@pytest.fixture(scope="module")
def search_out(tmp_path_factory, adversarial):
    """One proof-gated algogen search over the adversarial fabric,
    shared by the admission / registry / tune tests."""
    out_dir = str(tmp_path_factory.mktemp("algogen"))
    return out_dir, algogen.search(adversarial, out_dir=out_dir)


# ---------------------------------------------------------------------
# derivation + M4T206 admission
# ---------------------------------------------------------------------


def test_derive_beats_identity_on_adversarial_fabric(derived):
    assert derived["schema"] == placemod.SCHEMA
    assert derived["world"] == WORLD
    assert derived["perm"] != list(range(WORLD))
    assert sorted(derived["perm"]) == list(range(WORLD))
    assert derived["expected_s"] < derived["identity_s"]
    assert derived["gain"] is not None and derived["gain"] > 1.0
    assert derived["fingerprint"] == placemod.body_fingerprint(derived)


def test_derive_never_proposes_a_regression():
    # a uniform fabric has nothing to gain: derivation must fall back
    # to the always-admissible identity, never a speculative shuffle
    flat = topology.synthetic_map(
        topology.SyntheticLinkModel(4, beta_gbps=20.0)
    )
    doc = placemod.derive(flat)
    assert doc["gain"] is None or doc["gain"] <= 1.0 + 1e-9


def test_derived_perm_proves_m4t206_clean(derived):
    reports = placemod.verify(derived)
    assert placement_check.reports_clean(reports)
    provable = [r for r in reports if r.verdict != "unprovable"]
    # at minimum the canonical probe ring plus the shipped registry
    # algorithms feasible at world 8
    assert len(provable) >= 2
    assert all(r.verdict == "deadlock-free" for r in provable)


def test_non_bijection_is_an_m4t206_finding():
    reports = placement_check.check_permutation([0, 0, 1, 2], 4)
    assert not placement_check.reports_clean(reports)
    codes = {f.code for r in reports for f in r.findings}
    assert codes == {"M4T206"}
    msg = reports[0].findings[0].message
    assert "not a bijection" in msg


def test_perm_error_names_each_failure_mode():
    assert "2 entries" in placement_check.perm_error([0, 1], 4)
    assert "bijection" in placement_check.perm_error([0, 2], 2)
    assert "not a list of ints" in placement_check.perm_error(
        ["x", None], 2)
    assert placement_check.perm_error([1, 0], 2) is None


def test_infeasible_program_is_a_named_skip_not_a_verdict():
    # recursive doubling cannot run at world 3: the permutation has
    # nothing to break there, so the report is a named "unprovable"
    # skip and the probe ring still carries the proof
    rd = algomod.load(os.path.join(
        REPO, "mpi4jax_tpu", "planner", "algos", "recursive_double.json"
    ))
    probe = algomod.parse(dict(placement_check._PROBE_RING_RAW))
    reports = placement_check.check_permutation(
        [2, 0, 1], 3, specs=[probe, rd]
    )
    assert placement_check.reports_clean(reports)
    skipped = [r for r in reports if r.verdict == "unprovable"]
    assert len(skipped) == 1
    assert "infeasible at world 3" in skipped[0].reason


# ---------------------------------------------------------------------
# proof lifecycle: stamp, drift, persistence
# ---------------------------------------------------------------------


def test_proof_stamps_and_hand_edit_invalidates(proven):
    assert placemod.proof_mismatch(proven) is None
    proof = proven["proof"]
    assert proof["schema"] == placemod.PROOF_SCHEMA
    assert proof["rules"] == ["M4T206"]
    assert proof["verdict"] == "verified"
    edited = dict(proven, perm=list(reversed(proven["perm"])))
    drift = placemod.proof_mismatch(edited)
    assert drift is not None and "stale proof" in drift
    unproven = {k: v for k, v in proven.items() if k != "proof"}
    assert "unproven placement" in placemod.proof_mismatch(unproven)


def test_build_proof_refuses_unclean_reports():
    reports = placement_check.check_permutation([0, 0, 1, 2], 4)
    doc = {"schema": placemod.SCHEMA, "world": 4, "perm": [0, 0, 1, 2]}
    with pytest.raises(ValueError, match="placement not clean"):
        placemod.build_proof(doc, reports)


def test_save_load_roundtrip_and_tamper_detection(tmp_path, proven):
    path = str(tmp_path / "place.json")
    placemod.save(proven, path)
    loaded = placemod.load(path)
    assert loaded["perm"] == proven["perm"]
    assert placemod.proof_mismatch(loaded) is None
    with open(path) as f:
        doc = json.load(f)
    doc["perm"] = list(range(len(doc["perm"])))
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(placemod.PlacementError) as exc:
        placemod.load(path)
    assert exc.value.reason == "fingerprint"


def test_load_rejects_wrong_schema_and_bad_perm(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump({"schema": "nope"}, f)
    with pytest.raises(placemod.PlacementError) as exc:
        placemod.load(path)
    assert exc.value.reason == "schema"
    doc = {"schema": placemod.SCHEMA, "world": 3, "perm": [0, 1]}
    doc["fingerprint"] = placemod.body_fingerprint(doc)
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(placemod.PlacementError) as exc:
        placemod.load(path)
    assert exc.value.reason == "world"


# ---------------------------------------------------------------------
# the fingerprint-preservation property (satellite 4)
# ---------------------------------------------------------------------


def test_verified_permutation_preserves_fingerprints_1000_seeds():
    """Across 1000 random (world, permutation) draws, a permutation
    never changes any rank's schedule fingerprint sequence: physical
    rank ``perm[r]`` walks logical rank ``r``'s sequence verbatim.
    That is the invariant M4T206 certifies — checked here directly
    against the relabeling primitive, with the full simulator pass on
    a subsample."""
    spec = algomod.parse(dict(placement_check._PROBE_RING_RAW))
    cache = {}
    for seed in range(1000):
        rng = random.Random(seed)
        world = rng.randint(2, 8)
        perm = list(range(world))
        rng.shuffle(perm)
        if world not in cache:
            events = algomod.events_for(algomod.expand(spec, world))
            cache[world] = (
                events, placement_check.fingerprint_sequences(events)
            )
        events, seq_o = cache[world]
        permuted = placement_check.permute_events(events, perm)
        seq_p = placement_check.fingerprint_sequences(permuted)
        for r in range(world):
            assert seq_p[perm[r]] == seq_o[r], (seed, world, perm, r)
        if seed % 97 == 0:
            reports = placement_check.check_permutation(
                perm, world, specs=[spec]
            )
            assert placement_check.reports_clean(reports), (seed, perm)
    assert set(cache) == set(range(2, 9))  # every world was drawn


# ---------------------------------------------------------------------
# rule-catalog pins (satellite 4)
# ---------------------------------------------------------------------


def test_m4t206_joins_the_shared_rule_catalog():
    from mpi4jax_tpu.analysis import linter, sarif

    catalog = linter.rule_catalog()
    assert "M4T206 [error]" in catalog
    assert "schedule-equivalent" in catalog
    ids = [r["id"] for r in sarif._rules_meta()]
    assert "M4T206" in ids
    assert ids.index("M4T206") > ids.index("M4T205")


def test_analysis_cli_rules_lists_m4t206():
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis", "--rules"],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env=_clean_env(),
    )
    assert res.returncode == 0, res.stderr
    assert "M4T206" in res.stdout


# ---------------------------------------------------------------------
# arming + transparent application
# ---------------------------------------------------------------------


def test_apply_to_sequence_identity_unless_armed_and_matching(
    monkeypatch,
):
    monkeypatch.delenv(placemod.ENV_VAR, raising=False)
    assert placemod.apply_to_sequence(["a", "b"]) == ["a", "b"]
    monkeypatch.setenv(placemod.ENV_VAR, "1,0")
    assert placemod.apply_to_sequence(["a", "b"]) == ["b", "a"]
    # world mismatch: placement must never break a run it cannot help
    assert placemod.apply_to_sequence(["a", "b", "c"]) == ["a", "b", "c"]


def test_cartcomm_placement_remaps_wires_not_logic():
    from mpi4jax_tpu import comm as commmod

    perm = [0, 2, 1, 3]
    base = commmod.CartComm((4,), True)
    placed = commmod.CartComm((4,), True, placement=perm)
    assert placed.placement == tuple(perm)
    assert placed != base and hash(placed) != hash(base)
    # grid position p is hosted by physical rank perm[p]
    assert placed.rank_at((1,)) == 2
    assert placed.coords(2) == (1,)
    src0, dest0 = base.shift(0, 1)
    src, dest = placed.shift(0, 1)
    for p in range(4):
        # the identity wire tables, relabeled through the permutation
        assert dest[perm[p]] == perm[dest0[p]]
        assert src[perm[p]] == perm[src0[p]]


def test_cartcomm_rejects_non_bijection():
    from mpi4jax_tpu import comm as commmod

    with pytest.raises(ValueError, match="bijection"):
        commmod.CartComm((4,), True, placement=[0, 0, 1, 2])


def test_cartcomm_picks_up_armed_placement(monkeypatch):
    from mpi4jax_tpu import comm as commmod

    monkeypatch.setenv(placemod.ENV_VAR, "1,0,3,2")
    placed = commmod.CartComm((2, 2))
    assert placed.placement == (1, 0, 3, 2)


def test_world_mesh_applies_armed_placement(monkeypatch):
    from mpi4jax_tpu.parallel import mesh as meshmod

    monkeypatch.delenv(placemod.ENV_VAR, raising=False)
    base = list(meshmod.world_mesh().devices.flat)
    n = len(base)
    perm = list(reversed(range(n)))
    monkeypatch.setenv(
        placemod.ENV_VAR, ",".join(str(p) for p in perm)
    )
    placed = list(meshmod.world_mesh().devices.flat)
    assert placed == [base[p] for p in perm]


# ---------------------------------------------------------------------
# launch --place: simulator-verified-only, end to end
# ---------------------------------------------------------------------


def _manual_doc(perm, world):
    doc = {
        "schema": placemod.SCHEMA,
        "world": world,
        "perm": list(perm),
        "op": "AllReduce",
        "nbytes": 1 << 20,
        "method": "manual",
        "source": "test",
    }
    doc["fingerprint"] = placemod.body_fingerprint(doc)
    return doc


def _rank_script(tmp_path):
    target = str(tmp_path / "rank.py")
    with open(target, "w") as f:
        f.write(
            "import os\n"
            "print('PLACED=' + os.environ.get('M4T_PLACEMENT', 'none'))\n"
        )
    return target


def test_launch_place_blocks_unproven_doc_before_spawn(tmp_path):
    path = str(tmp_path / "place.json")
    placemod.save(_manual_doc([1, 0], 2), path)
    res = _launch("-n", "2", "--place", path, _rank_script(tmp_path))
    assert res.returncode == 1
    assert "BLOCKED" in res.stderr
    assert "no rank was spawned" in res.stderr
    assert "unproven placement" in res.stderr
    assert "PLACED=" not in res.stdout


def test_launch_place_blocks_tampered_doc_before_spawn(tmp_path):
    proven2 = placemod.prove(_manual_doc([1, 0], 2))
    # re-stamp the fingerprint after editing so only the *proof* is
    # stale — the launch gate must still refuse it
    tampered = dict(proven2, perm=[0, 1])
    tampered["fingerprint"] = placemod.body_fingerprint(tampered)
    path = str(tmp_path / "place.json")
    placemod.save(tampered, path)
    res = _launch("-n", "2", "--place", path, _rank_script(tmp_path))
    assert res.returncode == 1
    assert "BLOCKED" in res.stderr and "stale proof" in res.stderr
    assert "PLACED=" not in res.stdout


def test_launch_place_blocks_world_mismatch_before_spawn(tmp_path):
    proven4 = placemod.prove(_manual_doc([1, 0, 3, 2], 4))
    path = str(tmp_path / "place.json")
    placemod.save(proven4, path)
    res = _launch("-n", "2", "--place", path, _rank_script(tmp_path))
    assert res.returncode == 1
    assert "BLOCKED" in res.stderr
    assert "derived for world 4" in res.stderr
    assert "PLACED=" not in res.stdout


def test_launch_place_arms_verified_permutation_end_to_end(tmp_path):
    proven2 = placemod.prove(_manual_doc([1, 0], 2))
    path = str(tmp_path / "place.json")
    placemod.save(proven2, path)
    res = _launch("-n", "2", "--place", path, _rank_script(tmp_path))
    assert res.returncode == 0, res.stderr
    assert "arming M4T_PLACEMENT" in res.stderr
    # both ranks saw the armed permutation
    assert res.stdout.count("PLACED=1,0") == 2


# ---------------------------------------------------------------------
# placement CLI
# ---------------------------------------------------------------------


def test_cli_placement_derive_verify_show_roundtrip(tmp_path):
    topo_path = str(tmp_path / "topo.json")
    topology.save(topo_path, placemod.adversarial_topo(6))
    place_path = str(tmp_path / "place.json")
    res = _planner(
        "placement", "derive", "--topo", topo_path, "--out", place_path
    )
    assert res.returncode == 0, res.stderr
    assert "# perm" in res.stdout and "gain" in res.stdout
    assert "proven placement written" in res.stderr

    res = _planner("placement", "verify", place_path)
    assert res.returncode == 0, res.stderr

    res = _planner("placement", "show", place_path)
    assert res.returncode == 0
    assert "proven: True" in res.stdout

    # hand-edit: load refuses the fingerprint drift
    with open(place_path) as f:
        doc = json.load(f)
    doc["perm"] = list(reversed(doc["perm"]))
    with open(place_path, "w") as f:
        json.dump(doc, f)
    res = _planner("placement", "verify", place_path)
    assert res.returncode == 1
    assert "fingerprint" in res.stderr


def test_cli_placement_derive_bad_topo_exits_2(tmp_path):
    missing = str(tmp_path / "nope.json")
    res = _planner("placement", "derive", "--topo", missing)
    assert res.returncode == 2
    assert missing in res.stderr


def test_cli_placement_selftest():
    res = _planner("placement", "--selftest")
    assert res.returncode == 0, res.stderr + res.stdout
    assert "placement selftest ok" in res.stdout


# ---------------------------------------------------------------------
# algogen: proof-gated schedule-space search (the tentpole)
# ---------------------------------------------------------------------


def test_algogen_search_admits_a_topology_beating_candidate(search_out):
    out_dir, out = search_out
    assert out["worlds"] == [2, 4, 8]
    admitted = [c for c in out["candidates"] if c["verdict"] == "admitted"]
    assert admitted, out["candidates"]
    topo_ring = next(
        c for c in out["candidates"] if c["name"] == "gen-topo-ring"
    )
    assert topo_ring["verdict"] == "admitted"
    assert any(topo_ring["beats_ring"].values())
    assert topo_ring["proof_rules"] == [
        "M4T201", "M4T202", "M4T204", "M4T205"
    ]
    # it really is cheaper than the shipped ring under the measured
    # per-edge cost model at the fabric's world
    w = str(out["topo_world"])
    for b, beats in topo_ring["beats_ring"].items():
        if beats:
            assert (topo_ring["expected_s"][w][str(b)]
                    < topo_ring["baseline_ring_s"][str(b)])


def test_algogen_rejections_are_named_and_never_written(search_out):
    out_dir, out = search_out
    rejected = [
        c for c in out["candidates"] if c["verdict"] != "admitted"
    ]
    for c in rejected:
        assert c["verdict"].startswith("rejected:")
        assert "file" not in c
        assert not any(
            os.path.basename(p).startswith(c["name"])
            for p in out["written"]
        )


def test_algogen_written_files_register_unchanged(search_out, monkeypatch):
    out_dir, out = search_out
    assert out["written"]
    monkeypatch.setenv("M4T_ALGO_PATH", out_dir)
    algomod.invalidate_cache()
    reg = algomod.registry(refresh=True)
    for c in out["candidates"]:
        if c.get("file"):
            assert c["tag"] in reg, (c["tag"], sorted(reg))


def test_registry_refuses_unproven_generated_file(tmp_path, monkeypatch):
    # a generated spec dropped into the registry path *without* its
    # proof artifact must be rejected, not silently registered
    raw = algogen.tree_spec((2, 4, 8))
    path = str(tmp_path / "gen-tree.json")
    with open(path, "w") as f:
        json.dump(raw, f)
    monkeypatch.setenv("M4T_ALGO_PATH", str(tmp_path))
    algomod.invalidate_cache()
    reg = algomod.registry(refresh=True)
    assert not any("gen-tree" in tag for tag in reg)
    rejects = dict(algomod.registry_rejects())
    assert path in rejects
    assert "unproven" in rejects[path]


def test_tune_sweeps_generated_algos_on_equal_footing(
    search_out, tmp_path
):
    """Acceptance: the admitted generator output joins the tune sweep
    next to the built-ins and wins buckets on the adversarial fabric
    under ``expected_time_topo``."""
    out_dir, out = search_out
    topo_path = str(tmp_path / "topo.json")
    topology.save(topo_path, placemod.adversarial_topo(WORLD))
    cache = str(tmp_path / "plan.json")
    env = _clean_env()
    env["M4T_ALGO_PATH"] = out_dir
    res = _planner(
        "tune", "--cache", cache, "--topo", topo_path,
        "--world", str(WORLD), "--dtypes", "float32",
        "--ops", "AllReduce", env=env,
    )
    assert res.returncode == 0, res.stderr
    planobj = planmod.load(cache)
    impls = {e.impl for e in planobj.entries.values()}
    assert any(i.startswith("algo:gen-") for i in impls), impls


def test_cli_algogen_search_writes_admitted_candidates(tmp_path):
    topo_path = str(tmp_path / "topo.json")
    topology.save(topo_path, placemod.adversarial_topo(WORLD))
    out_dir = str(tmp_path / "algos")
    res = _planner(
        "algogen", "search", "--topo", topo_path, "--out", out_dir,
        "--worlds", "2,4,8", timeout=480,
    )
    assert res.returncode == 0, res.stderr
    assert "gen-topo-ring" in res.stdout
    assert "beats_ring=" in res.stdout
    files = sorted(os.listdir(out_dir))
    assert any(f.endswith(".proof.json") for f in files)
    for f in files:
        if f.endswith(".json") and not f.endswith(".proof.json"):
            assert f.replace(".json", ".proof.json") in files


def test_cli_algogen_selftest():
    res = _planner("algogen", "--selftest", timeout=480)
    assert res.returncode == 0, res.stderr + res.stdout
    assert "algogen selftest ok" in res.stdout


# ---------------------------------------------------------------------
# plan-cache provenance
# ---------------------------------------------------------------------


def test_plan_placement_roundtrips_and_old_ids_stay_stable(
    tmp_path, proven
):
    bare = planmod.Plan(platform="cpu")
    with_place = planmod.Plan(platform="cpu", placement=proven)
    # plans without a placement keep their pre-placement identity:
    # the canonical body only grows the key when one is attached
    assert "placement" not in planmod._canonical_body("cpu", {})
    assert "placement" in planmod._canonical_body(
        "cpu", {}, placement=proven
    )
    assert bare.plan_id != with_place.plan_id
    path = str(tmp_path / "plan.json")
    planmod.save(with_place, path)
    loaded = planmod.load(path)
    assert loaded.placement == proven
    assert loaded.plan_id == with_place.plan_id


def test_plan_merge_carries_placement(proven):
    base = planmod.Plan(platform="cpu", placement=proven)
    update = planmod.Plan(platform="cpu")
    merged = planmod.merge(base, update)
    assert merged.placement == proven
    base2 = planmod.Plan(platform="cpu")
    merged2 = planmod.merge(base2, planmod.Plan(
        platform="cpu", placement=proven,
    ))
    assert merged2.placement == proven


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
