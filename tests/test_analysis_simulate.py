"""Schedule simulator (analysis/simulate.py): deadlock/mismatch
verdicts with concrete witnesses, property-based agreement with a
brute-force blocking-semantics matcher, the golden JSON schema pin for
``--simulate --json``, the self-verify gate over every registered
lint target at ranks in {2, 4, 8}, SARIF export, ``launch --verify``
as a pre-spawn gate, and the doctor's simulated schedule positions.

Regenerate the golden after an intentional schema change::

    python tests/test_analysis_simulate.py --regen
"""

import importlib
import json
import os
import random
import subprocess
import sys
import textwrap

import pytest

from mpi4jax_tpu.analysis.__main__ import _import_target
from mpi4jax_tpu.analysis.__main__ import main as lint_main
from mpi4jax_tpu.analysis.schedule import ScheduleEvent
from mpi4jax_tpu.analysis.simulate import (
    sim_reports_to_json,
    simulate_events,
    verify_module,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "data", "simulate_fixture.py")
GOLDEN = os.path.join(HERE, "data", "simulate_golden.json")

MODEL_MODULES = (
    "mpi4jax_tpu.models.mlp",
    "mpi4jax_tpu.models.attention",
    "mpi4jax_tpu.models.shallow_water",
)
EXAMPLE_FILES = (
    "examples/cg_solver.py",
    "examples/zero_optimizer.py",
    "examples/train_transformer.py",
    "examples/shallow_water.py",
)


def C(fp, group, edges=()):
    """Synthetic group-synchronizing collective event."""
    edges = tuple(tuple(e) for e in edges)
    return ScheduleEvent(
        op="AllReduce", fingerprint=fp, kind="collective",
        group=tuple(group), edges=edges,
        sends=tuple(d for s, d in edges),
        recvs=tuple(s for s, d in edges),
    )


def P(fp, sends=(), recvs=()):
    """Synthetic blocking point-to-point event (unbuffered)."""
    return ScheduleEvent(
        op="Sendrecv", fingerprint=fp, kind="p2p", group=(),
        sends=tuple(sends), recvs=tuple(recvs),
    )


# -- simulator semantics on synthetic schedules -----------------------


def test_matching_collectives_complete():
    ok, rounds, findings = simulate_events(
        {0: [C("A", (0, 1))], 1: [C("A", (0, 1))]}
    )
    assert ok and findings == [] and rounds == 1


def test_collective_fingerprint_mismatch_is_m4t202():
    ok, _, findings = simulate_events(
        {0: [C("A", (0, 1))], 1: [C("B", (0, 1))]}
    )
    assert not ok
    assert [f.code for f in findings] == ["M4T202"]
    w = findings[0].witness
    assert w["fingerprints"] == {"0": "A", "1": "B"}


def test_crossed_p2p_is_m4t201_cycle():
    # rank0: send->1 then recv<-1; rank1: send->0 then recv<-0 —
    # the canonical crossed unbuffered send/recv
    ok, _, findings = simulate_events(
        {
            0: [P("A", sends=(1,)), P("A", recvs=(1,))],
            1: [P("A", sends=(0,)), P("A", recvs=(0,))],
        }
    )
    assert not ok
    (f,) = findings
    assert f.code == "M4T201"
    assert f.witness["is_cycle"]
    assert sorted(f.witness["cycle"]) == [0, 1]


def test_sendrecv_exchange_completes():
    # the same transfer expressed as a simultaneous exchange is fine
    ok, _, findings = simulate_events(
        {
            0: [P("A", sends=(1,), recvs=(1,))],
            1: [P("A", sends=(0,), recvs=(0,))],
        }
    )
    assert ok and findings == []


def test_three_rank_chain_completes():
    # rank1 sendrecv(send->0, recv<-2); rank0 recv<-1; rank2 send->1:
    # MPI posting semantics, no barrier needed
    ok, _, findings = simulate_events(
        {
            0: [P("A", recvs=(1,))],
            1: [P("A", sends=(0,), recvs=(2,))],
            2: [P("A", sends=(1,))],
        }
    )
    assert ok and findings == []


def test_allreduce_vs_waiting_recv_is_m4t201():
    # rank 0 enters a collective while its peer waits in a recv
    ok, _, findings = simulate_events(
        {0: [C("A", (0, 1))], 1: [P("B", recvs=(0,))]}
    )
    assert not ok
    (f,) = findings
    assert f.code == "M4T201"


def test_wait_on_finished_rank_is_m4t201():
    ok, _, findings = simulate_events(
        {0: [C("A", (0, 1)), C("A", (0, 1))], 1: [C("A", (0, 1))]}
    )
    assert not ok
    (f,) = findings
    assert f.code == "M4T201"
    states = {r["rank"]: r["state"] for r in f.witness["ranks"]}
    assert states[0] == "blocked" and states[1] == "finished"


def test_crossed_permute_same_fingerprint_is_m4t201():
    # divergent branches executing different permutes share a
    # fingerprint but not edges — deadlock, not mismatch
    ok, _, findings = simulate_events(
        {
            0: [C("A", (0, 1), edges=((0, 1),))],
            1: [C("A", (0, 1), edges=((1, 0),))],
        }
    )
    assert not ok
    (f,) = findings
    assert f.code == "M4T201"


def test_independent_subgroups_interleave():
    # two disjoint groups progress independently, in any order
    ok, rounds, findings = simulate_events(
        {
            0: [C("A", (0, 1)), C("X", (0, 1, 2, 3))],
            1: [C("A", (0, 1)), C("X", (0, 1, 2, 3))],
            2: [C("B", (2, 3)), C("B", (2, 3)), C("X", (0, 1, 2, 3))],
            3: [C("B", (2, 3)), C("B", (2, 3)), C("X", (0, 1, 2, 3))],
        }
    )
    assert ok and findings == []


# -- property-based: agreement with a brute-force matcher -------------


def _brute_force_free(events, rng):
    """Independent implementation of the blocking semantics: snapshot
    the parked positions, advance every individually completable rank
    (visiting in random order), repeat. Monotone system => the verdict
    is schedule-order independent."""
    pcs = {r: 0 for r in events}

    def parked(snap, g):
        if g not in events or snap[g] >= len(events[g]):
            return None
        return events[g][snap[g]]

    while any(pcs[r] < len(events[r]) for r in events):
        snap = dict(pcs)
        movers = []
        order = list(events)
        rng.shuffle(order)
        for r in order:
            e = parked(snap, r)
            if e is None:
                continue
            if e.kind == "collective":
                good = all(
                    (lambda pg: pg is not None
                     and pg.kind == "collective"
                     and pg.fingerprint == e.fingerprint
                     and pg.group == e.group
                     and pg.edges == e.edges)(parked(snap, g))
                    for g in e.group
                )
            else:
                good = True
                for d in e.sends:
                    if d == r:
                        good = good and (r in e.recvs)
                        continue
                    pd = parked(snap, d)
                    good = good and (
                        pd is not None and pd.kind == "p2p"
                        and r in pd.recvs and pd.fingerprint == e.fingerprint
                    )
                for s in e.recvs:
                    if s == r:
                        continue
                    ps = parked(snap, s)
                    good = good and (
                        ps is not None and ps.kind == "p2p"
                        and r in ps.sends and ps.fingerprint == e.fingerprint
                    )
            if good:
                movers.append(r)
        if not movers:
            return False
        for r in movers:
            pcs[r] += 1
    return True


def _random_schedule(rng):
    n = rng.randint(2, 4)
    events = {r: [] for r in range(n)}
    fps = ["A", "B", "C"]
    for _ in range(rng.randint(0, 5)):
        if rng.random() < 0.5:
            fp = rng.choice(fps)
            roll = rng.random()
            bad_rank = rng.randrange(n)
            for r in range(n):
                myfp = fp
                if roll < 0.15 and r == bad_rank:
                    myfp = rng.choice([f for f in fps if f != fp])
                if 0.15 <= roll < 0.28 and r == bad_rank:
                    continue  # this rank skips the collective
                events[r].append(C(myfp, range(n)))
        else:
            fp = rng.choice(fps)
            perm = list(range(n))
            rng.shuffle(perm)
            inv = [perm.index(r) for r in range(n)]
            flip = rng.randrange(n) if rng.random() < 0.25 else None
            for r in range(n):
                sends, recvs = (perm[r],), (inv[r],)
                if r == flip:
                    sends, recvs = recvs, sends
                events[r].append(P(fp, sends, recvs))
    return events


def test_property_simulator_agrees_with_brute_force():
    """~1k seeded random per-rank schedules: the optimized simulator's
    deadlock-free verdict must agree with the brute-force matcher on
    every one (and stuck states must always classify into a finding)."""
    rng = random.Random(20260804)
    for case in range(1000):
        events = _random_schedule(rng)
        ok, _, findings = simulate_events(
            {r: list(ev) for r, ev in events.items()}
        )
        expected = _brute_force_free(events, rng)
        assert ok == expected, f"case {case}: sim={ok} brute={expected}"
        if not ok:
            assert findings, f"case {case}: stuck but no witness"
            assert all(f.code in ("M4T201", "M4T202") for f in findings)


# -- verify drivers on the fixture ------------------------------------


def _fixture_reports(world=None):
    module, _fn = _import_target(FIXTURE)
    return verify_module(module, world=world)


def test_fixture_verdicts():
    by_name = {
        r.target.split(":")[-1]: r for r in _fixture_reports()
    }
    assert by_name["clean"].deadlock_free
    assert [f.code for f in by_name["crossed"].findings] == ["M4T201"]
    assert [f.code for f in by_name["mismatch"].findings] == ["M4T202"]
    assert [f.code for f in by_name["redundant"].findings] == ["M4T203"]


def test_crossed_witness_names_the_cycle_and_sources():
    rep = {
        r.target.split(":")[-1]: r for r in _fixture_reports()
    }["crossed"]
    (f,) = rep.findings
    assert f.witness["is_cycle"]
    assert sorted(f.witness["cycle"]) == [0, 1]
    for entry in f.witness["ranks"]:
        assert "simulate_fixture.py" in entry["source"]


# -- golden JSON schema pin -------------------------------------------


def _normalize(obj, root):
    if isinstance(obj, str):
        return obj.replace(root + os.sep, "")
    if isinstance(obj, list):
        return [_normalize(v, root) for v in obj]
    if isinstance(obj, dict):
        return {k: _normalize(v, root) for k, v in obj.items()}
    return obj


def _fixture_sim_json():
    obj = sim_reports_to_json(_fixture_reports())
    return json.loads(json.dumps(_normalize(obj, REPO), sort_keys=True))


def test_simulate_golden_file():
    """The exact ``--simulate --json`` payload for the fixed fixture is
    pinned — schema drift must be intentional (same pattern as
    lint_golden.json)."""
    produced = _fixture_sim_json()
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert produced == golden


# -- the self-verify gate ---------------------------------------------


@pytest.mark.parametrize("world", (2, 4, 8))
@pytest.mark.parametrize("modname", MODEL_MODULES)
def test_models_proved_deadlock_free(modname, world):
    reports = verify_module(
        importlib.import_module(modname), world=world
    )
    assert reports, f"{modname} has no target at world {world}"
    for rep in reports:
        assert rep.deadlock_free, f"{rep.target}:\n{rep.to_text()}"
        assert rep.world == world


@pytest.mark.parametrize("world", (2, 4, 8))
@pytest.mark.parametrize("relpath", EXAMPLE_FILES)
def test_examples_proved_deadlock_free(relpath, world):
    module, _fn = _import_target(os.path.join(REPO, relpath))
    reports = verify_module(module, world=world)
    assert reports, f"{relpath} has no target at world {world}"
    for rep in reports:
        assert rep.deadlock_free, f"{rep.target}:\n{rep.to_text()}"


# -- CLI ---------------------------------------------------------------


def test_cli_simulate_clean_exits_0(capsys):
    rc = lint_main(["mpi4jax_tpu.models.mlp", "--simulate"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PROVED deadlock-free" in out


def test_cli_simulate_fixture_exits_1_with_witness(capsys):
    rc = lint_main([FIXTURE, "--simulate"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "M4T201" in out and "rank cycle" in out
    assert "M4T202" in out and "M4T203" in out


def test_cli_ranks_sweep(capsys):
    rc = lint_main(["mpi4jax_tpu.models.mlp", "--simulate", "--ranks", "2,4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "world 2" in out and "world 4" in out


def test_cli_cost_report(capsys):
    rc = lint_main(["mpi4jax_tpu.models.shallow_water", "--cost"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "static cost" in out and "dominant collectives" in out


def test_cli_simulate_json_schema(capsys):
    rc = lint_main([FIXTURE, "--simulate", "--json"])
    assert rc == 1
    obj = json.loads(capsys.readouterr().out)
    assert "simulate" in obj
    verdicts = {
        r["target"].split(":")[-1]: r["verdict"]
        for r in obj["simulate"]["reports"]
    }
    assert verdicts["clean"] == "deadlock-free"
    assert verdicts["crossed"] == "findings"


def test_cli_sarif_output(tmp_path, capsys):
    out_path = str(tmp_path / "findings.sarif")
    rc = lint_main([FIXTURE, "--simulate", "--sarif", out_path])
    assert rc == 1
    with open(out_path) as f:
        sarif = json.load(f)
    assert sarif["version"] == "2.1.0"
    (run,) = sarif["runs"]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"M4T101", "M4T201", "M4T202", "M4T203"} <= rule_ids
    results = run["results"]
    assert any(r["ruleId"] == "M4T201" for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("simulate_fixture.py")
    assert loc["region"]["startLine"] > 1


def test_cli_rules_lists_m4t2xx(capsys):
    rc = lint_main(["--rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for code in ("M4T201", "M4T202", "M4T203"):
        assert code in out


# -- launch --verify ---------------------------------------------------


def _write_fixture_copy(tmp_path, body):
    path = str(tmp_path / "script.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(body))
    return path


_DEADLOCK_SCRIPT = """
    import sys

    def _lint_bad(world: int = 2):
        import jax, jax.numpy as jnp
        from jax import lax
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.analysis import LintTarget
        n = world

        def step(x):
            r = lax.axis_index("ranks")

            def evens(v):
                dest = tuple((k + 1) if k % 2 == 0 else -1 for k in range(n))
                src = tuple((k - 1) if k % 2 == 1 else -1 for k in range(n))
                return m4t.sendrecv(v, v, src, dest, sendtag=1)

            def odds(v):
                dest = tuple((k - 1) if k % 2 == 1 else -1 for k in range(n))
                src = tuple((k + 1) if k % 2 == 0 else -1 for k in range(n))
                return m4t.sendrecv(v, v, src, dest, sendtag=1)

            return lax.cond(r % 2 == 0, evens, odds, x)

        return LintTarget(
            fn=step,
            args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
            axis_env={"ranks": n},
        )

    M4T_LINT_TARGETS = {"bad": _lint_bad}

    if __name__ == "__main__":
        print("RANK_RAN")  # must never appear under --verify
        sys.exit(0)
"""


def test_launch_verify_blocks_deadlock_before_spawn(tmp_path):
    """Acceptance: the seeded crossed-sendrecv fixture is flagged
    M4T201 with a rank-cycle witness and blocked by ``launch --verify``
    before any rank spawns."""
    path = _write_fixture_copy(tmp_path, _DEADLOCK_SCRIPT)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--verify", path],
        env=env, capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert res.returncode == 1
    assert "M4T201" in res.stderr and "rank cycle" in res.stderr
    assert "BLOCKED" in res.stderr
    assert "RANK_RAN" not in res.stdout  # no rank ever spawned


def test_launch_verify_reports_unimportable_target(tmp_path):
    path = str(tmp_path / "nope.py")
    with open(path, "w") as f:
        f.write("raise RuntimeError('boom at import')\n")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--verify", path],
        capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert res.returncode == 1
    assert "cannot import" in res.stderr


# -- doctor --static: simulated schedule positions --------------------


def test_doctor_hang_cites_simulated_schedule_position(tmp_path):
    from mpi4jax_tpu.observability import doctor

    def emission(rank, seq, op, shape):
        return {
            "kind": "emission", "rank": rank, "seq": seq, "op": op,
            "shape": shape, "dtype": "float32", "axes": ["ranks"],
            "world": 2, "bytes": 32, "t": 100.0 + seq,
        }

    # rank 0 completed AllReduce+AllGather; rank 1 stopped after the
    # AllReduce — its simulated schedule says AllGather comes next
    logs = {
        0: [emission(0, 1, "AllReduce", [8]),
            emission(0, 2, "AllGather", [8])],
        1: [emission(1, 1, "AllReduce", [8])],
    }
    for rank, records in logs.items():
        with open(tmp_path / f"events-rank{rank}.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    report = doctor.diagnose([str(tmp_path)])
    hangs = [f for f in report["findings"] if f["kind"] == "hang"]
    assert hangs and hangs[0]["rank"] == 1
    schedules = doctor.collect_static_schedules(FIXTURE, world=2)
    assert schedules
    joined = doctor.attach_schedule_positions(report, schedules)
    assert joined == 1
    sp = hangs[0]["schedule_position"]
    assert sp["position"] == 1
    assert sp["expected_next"]["op"] == "AllGather"
    assert "simulate_fixture.py" in sp["expected_next"]["source"]
    # and the text report prints it
    txt = doctor.format_report(report)
    assert "simulated schedule" in txt and "should next emit" in txt


if __name__ == "__main__":
    if "--regen" in sys.argv:
        with open(GOLDEN, "w") as f:
            json.dump(_fixture_sim_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"golden rewritten: {GOLDEN}")
    else:
        print("usage: python tests/test_analysis_simulate.py --regen")
