"""Ring attention and Ulysses sequence parallelism: distributed exact
attention must match single-device full attention (the correctness
oracle for the long-context subsystem; SURVEY.md §5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.parallel import ring_attention, ulysses_attention

N = 8
T_LOCAL = 4
T = N * T_LOCAL
D = 16
H = 8


def reference_attention(q, k, v, causal=False):
    s = (q @ k.T).astype(np.float32) * D**-0.5
    if causal:
        mask = np.tril(np.ones(s.shape, bool))
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return p @ v


def reference_mha(q, k, v, causal=False):
    # q,k,v: (T, H, D)
    outs = [
        reference_attention(q[:, h], k[:, h], v[:, h], causal) for h in range(H)
    ]
    return np.stack(outs, axis=1)


@pytest.fixture()
def qkv():
    rng = np.random.RandomState(3)
    q = rng.randn(T, D).astype(np.float32)
    k = rng.randn(T, D).astype(np.float32)
    v = rng.randn(T, D).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(run_spmd, qkv, causal):
    q, k, v = qkv
    expected = reference_attention(q, k, v, causal)

    def shard(a):
        return a.reshape(N, T_LOCAL, D)

    out = run_spmd(
        lambda ql, kl, vl: ring_attention(ql, kl, vl, causal=causal),
        shard(q), shard(k), shard(v),
    )
    np.testing.assert_allclose(out.reshape(T, D), expected, rtol=2e-4, atol=2e-5)


def test_ring_attention_single_device(qkv):
    q, k, v = qkv
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    np.testing.assert_allclose(
        np.asarray(out), reference_attention(q, k, v), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(run_spmd, causal):
    rng = np.random.RandomState(7)
    q = rng.randn(T, H, D).astype(np.float32)
    k = rng.randn(T, H, D).astype(np.float32)
    v = rng.randn(T, H, D).astype(np.float32)
    expected = reference_mha(q, k, v, causal)

    def shard(a):
        return a.reshape(N, T_LOCAL, H, D)

    out = run_spmd(
        lambda ql, kl, vl: ulysses_attention(ql, kl, vl, causal=causal),
        shard(q), shard(k), shard(v),
    )
    np.testing.assert_allclose(
        out.reshape(T, H, D), expected, rtol=2e-4, atol=2e-5
    )


def test_ring_attention_grad(run_spmd, qkv):
    """Differentiability through the ring (the sendrecv JVP/transpose
    rules composed under fori_loop)."""
    q, k, v = qkv

    def shard(a):
        return a.reshape(N, T_LOCAL, D)

    def f(ql, kl, vl):
        return jax.grad(
            lambda qq: (ring_attention(qq, kl, vl) ** 2).sum()
        )(ql)

    out = run_spmd(f, shard(q), shard(k), shard(v))

    jq = jnp.asarray(q)
    expected = jax.grad(
        lambda qq: (
            jnp.asarray(reference_attention_jnp(qq, jnp.asarray(k), jnp.asarray(v)))
            ** 2
        ).sum()
    )(jq)
    np.testing.assert_allclose(
        out.reshape(T, D), np.asarray(expected), rtol=5e-3, atol=5e-4
    )


def reference_attention_jnp(q, k, v):
    s = (q @ k.T).astype(jnp.float32) * D**-0.5
    p = jax.nn.softmax(s, axis=-1)
    return p @ v
