"""Quantized (int8-wire) ring all-reduce: bounded error vs the exact
allreduce, exactness for representable values."""

import numpy as np

import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu.ops.quantized import quantized_allreduce

N = 8

from tests.conftest import needs_size1_world



def test_quantized_allreduce_error_bound(run_spmd, per_rank):
    rng = np.random.RandomState(0)
    arr = rng.randn(N, 4096).astype(np.float32)
    out = run_spmd(lambda x: quantized_allreduce(x), jnp.asarray(arr))
    exact = arr.sum(axis=0)
    scale = np.abs(exact).max()
    for r in range(N):
        err = np.abs(out[r] - exact).max() / scale
        assert err < 0.05, err
    # all ranks agree exactly (same wire data)
    np.testing.assert_array_equal(out[0], out[3])


def test_quantized_allreduce_exact_for_representable(run_spmd, per_rank):
    # integers well within int8 round-trip exactly at every hop
    arr = per_rank(lambda r: np.full(512, float(r + 1), np.float32))
    out = run_spmd(lambda x: quantized_allreduce(x), arr)
    np.testing.assert_allclose(out[0], np.full(512, arr[:, 0].sum()), rtol=1e-6)


def test_quantized_allreduce_unaligned_size(run_spmd, per_rank):
    rng = np.random.RandomState(1)
    arr = rng.randn(N, 777).astype(np.float32)  # not block/chunk aligned
    out = run_spmd(lambda x: quantized_allreduce(x), jnp.asarray(arr))
    exact = arr.sum(axis=0)
    err = np.abs(out[0] - exact).max() / max(np.abs(exact).max(), 1e-6)
    assert err < 0.05


@needs_size1_world
def test_quantized_allreduce_size1():
    x = jnp.arange(10.0)
    np.testing.assert_allclose(quantized_allreduce(x), x)


def test_quantized_allreduce_zero_input(run_spmd, per_rank):
    arr = per_rank(lambda r: np.zeros(256, np.float32))
    out = run_spmd(lambda x: quantized_allreduce(x), arr)
    np.testing.assert_array_equal(out[0], np.zeros(256))
