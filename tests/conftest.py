"""Test harness: 8 virtual CPU devices standing in for an 8-chip mesh.

The reference runs its whole pytest suite twice — single-process and
under ``mpirun -np 2`` (``docs/developers.rst:18-27``). The TPU-native
analog (SURVEY.md §4 closing note): the same suite runs single-rank
(eager, world size 1) and over an
``--xla_force_host_platform_device_count=8`` CPU mesh via ``shard_map``.
"""

import os

# The suite must collect (and mostly run) even on containers whose jax
# predates jax_compat.MINIMUM_JAX — the seed state was a full-suite
# collection failure on exactly such a container. The suite itself is
# the compatibility evidence, so the test harness opts in to the
# version-gate escape hatch; library users still hit the hard gate.
os.environ.setdefault("MPI4JAX_TPU_SKIP_VERSION_CHECK", "1")

# Must happen before the first backend initialization. The container's
# sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu"; re-force cpu below after import.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from mpi4jax_tpu.parallel import spmd, world_mesh  # noqa: E402
from mpi4jax_tpu.runtime import shm as _shm  # noqa: E402

N_RANKS = 8

# Reference idiom (its tests read rank/size from COMM_WORLD at module
# level so one file is valid at any world size): standalone the eager
# world is size 1; under `python -m mpi4jax_tpu.launch -n N` it is N.
# Test modules import these from tests.conftest.
IN_LAUNCHER_WORLD = _shm.active()
WORLD = _shm.size() if IN_LAUNCHER_WORLD else 1
MY_RANK = _shm.rank() if IN_LAUNCHER_WORLD else 0

#: skip for cases that assume a size-1 eager world (analog of the
#: reference's size-conditional skipifs)
needs_size1_world = pytest.mark.skipif(
    IN_LAUNCHER_WORLD, reason="assumes a size-1 eager world (launcher world active)"
)

from mpi4jax_tpu import jax_compat as _jax_compat  # noqa: E402

#: is the ambient jax older than the supported floor? (The suite runs
#: under the MPI4JAX_TPU_SKIP_VERSION_CHECK escape hatch, above, so it
#: collects and mostly passes on such containers; the few tests that
#: genuinely need post-0.6 APIs — pallas platform_dependent lowering,
#: AbstractMesh.manual_axes, x64 interpret-mode bit-exactness — carry
#: this skip instead of failing as false alarms.)
JAX_BELOW_MINIMUM = _jax_compat.versiontuple(
    jax.__version__
) < _jax_compat.versiontuple(_jax_compat.MINIMUM_JAX)

needs_supported_jax = pytest.mark.skipif(
    JAX_BELOW_MINIMUM,
    reason=(
        f"requires jax>={_jax_compat.MINIMUM_JAX} "
        f"(found {jax.__version__}; running under the version-gate "
        "escape hatch)"
    ),
)


from mpi4jax_tpu import token as _token  # noqa: E402


@pytest.fixture(autouse=True)
def _fail_on_leaked_sends(request):
    """Token-discipline teardown check: a test that issues a ``send``
    whose ``recv`` never appears leaves the transfer silently
    unemitted, and (pre-this-fixture) the failure would surface as a
    confusing RuntimeWarning/poisoned-trace error in whichever *later*
    test evicted the stale trace state. Drain the channel state around
    every test so the leaking test fails itself. Tests that leak on
    purpose opt out with ``@pytest.mark.allow_pending_sends``."""
    _token.drain_pending_sends()  # isolate from anything earlier
    yield
    leaks = _token.drain_pending_sends()
    if leaks and request.node.get_closest_marker("allow_pending_sends") is None:
        tags = [rec["tag"] for _key, recs in leaks for rec in recs]
        n = sum(len(recs) for _key, recs in leaks)
        pytest.fail(
            f"test leaked {n} unmatched send(s) (tags {tags}): every "
            "send must pair with a recv in the same traced program "
            "(mpi4jax_tpu/ops/p2p.py; token.check_no_pending_sends)"
        )


def pytest_report_header(config):
    # Analog of the reference's vendor/rank/size header
    # (tests/conftest.py:1-9 in the reference).
    return (
        f"mpi4jax_tpu harness: {len(jax.devices())} virtual CPU devices, "
        f"world size {N_RANKS}"
    )


@pytest.fixture(scope="session")
def mesh():
    m = world_mesh()
    assert m.devices.size == N_RANKS
    return m


@pytest.fixture()
def run_spmd(mesh):
    """Run a per-rank function over the 8-rank mesh.

    ``run_spmd(fn, *args)``: each arg has leading axis 8 (per-rank
    blocks); returns stacked per-rank outputs as numpy arrays.
    """

    def runner(fn, *args):
        out = spmd(fn, mesh=mesh)(*args)
        return jax.tree.map(np.asarray, out)

    return runner


@pytest.fixture()
def per_rank():
    """Build a stacked per-rank input: per_rank(fn) with fn(rank)->arr."""

    def build(fn):
        return np.stack([np.asarray(fn(r)) for r in range(N_RANKS)])

    return build
