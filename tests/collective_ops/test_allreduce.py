"""Transform-matrix coverage for allreduce, mirroring the reference's
``tests/collective_ops/test_allreduce.py`` (322 LoC: plain / jit /
scalar / vmap / grad / jvp / vjp / linear_transpose / double+triple
transpose, analytic oracles ``arr * size``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4t

from tests.conftest import WORLD

N = 8


def base_arr(rank):
    return np.ones((3, 2), np.float32) * (rank + 1)


def test_allreduce_sum(run_spmd, per_rank):
    arr = per_rank(base_arr)
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected)


def test_allreduce_input_not_mutated(run_spmd, per_rank):
    # Reference invariant: inputs never mutated (test_allreduce.py:17-21).
    arr = per_rank(base_arr)
    keep = arr.copy()
    run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
    np.testing.assert_array_equal(arr, keep)


def test_allreduce_scalar(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r + 1))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr)
    np.testing.assert_allclose(out, np.full(N, arr.sum()))


@pytest.mark.parametrize(
    "op,np_red",
    [
        (m4t.SUM, np.sum),
        (m4t.MAX, np.max),
        (m4t.MIN, np.min),
        (m4t.PROD, np.prod),
    ],
)
def test_allreduce_ops(run_spmd, per_rank, op, np_red):
    arr = per_rank(lambda r: np.arange(1, 5, dtype=np.float32) + r)
    out = run_spmd(lambda x: m4t.allreduce(x, op=op), arr)
    expected = np_red(arr, axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


@pytest.mark.parametrize(
    "op,oracle",
    [
        (m4t.LAND, lambda a: np.all(a != 0, axis=0)),
        (m4t.LOR, lambda a: np.any(a != 0, axis=0)),
        (m4t.BAND, lambda a: np.bitwise_and.reduce(a, axis=0)),
        (m4t.BOR, lambda a: np.bitwise_or.reduce(a, axis=0)),
        (m4t.BXOR, lambda a: np.bitwise_xor.reduce(a, axis=0)),
    ],
)
def test_allreduce_logical_ops(run_spmd, per_rank, op, oracle):
    arr = per_rank(lambda r: (np.arange(6) + r) % 3).astype(np.int32)
    out = run_spmd(lambda x: m4t.allreduce(x, op=op), arr)
    expected = oracle(arr).astype(out.dtype)
    for r in range(N):
        np.testing.assert_array_equal(out[r], expected)


def test_allreduce_int_and_bool(run_spmd, per_rank):
    arr_i = per_rank(lambda r: np.arange(4, dtype=np.int32) + r)
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr_i)
    np.testing.assert_array_equal(out[0], arr_i.sum(axis=0))

    arr_b = per_rank(lambda r: np.array([r % 2 == 0, False, True]))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), arr_b)
    np.testing.assert_array_equal(out[0], arr_b.any(axis=0))


def test_allreduce_vmap(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(12, dtype=np.float32).reshape(4, 3) + r)
    out = run_spmd(
        lambda x: jax.vmap(lambda y: m4t.allreduce(y, op=m4t.SUM))(x), arr
    )
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected)


def test_allreduce_grad(run_spmd, per_rank):
    # Reference: grad of sum(allreduce(x)) is ones (test_allreduce.py:141-193
    # — the transpose-is-identity convention).
    arr = per_rank(base_arr)
    out = run_spmd(
        lambda x: jax.grad(lambda y: m4t.allreduce(y, op=m4t.SUM).sum())(x), arr
    )
    np.testing.assert_allclose(out, np.ones_like(arr))


def test_allreduce_value_and_grad(run_spmd, per_rank):
    arr = per_rank(base_arr)

    def f(x):
        v, g = jax.value_and_grad(lambda y: m4t.allreduce(y, op=m4t.SUM).sum())(x)
        return v * jnp.ones(()), g

    val, grad = run_spmd(f, arr)
    np.testing.assert_allclose(val, np.full(N, arr.sum(axis=0).sum()))
    np.testing.assert_allclose(grad, np.ones_like(arr))


def test_allreduce_jvp(run_spmd, per_rank):
    # JVP = allreduce of the tangents (reference allreduce.py:138-149).
    arr = per_rank(base_arr)

    def f(x):
        p, t = jax.jvp(lambda y: m4t.allreduce(y, op=m4t.SUM), (x,), (x,))
        return p, t

    p, t = run_spmd(f, arr)
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(p[r], expected)
        np.testing.assert_allclose(t[r], expected)


def test_allreduce_vjp(run_spmd, per_rank):
    # VJP pullback of replicated cotangent = identity per rank
    # (reference transpose convention, allreduce.py:152-159).
    arr = per_rank(base_arr)

    def f(x):
        p, vjp_fun = jax.vjp(lambda y: m4t.allreduce(y, op=m4t.SUM), x)
        (ct,) = vjp_fun(jnp.ones_like(p))
        return p, ct

    p, ct = run_spmd(f, arr)
    np.testing.assert_allclose(ct, np.ones_like(arr))


def test_allreduce_transpose_identity(run_spmd, per_rank):
    # linear_transpose(allreduce)(ct) == ct (reference
    # test_allreduce.py:105-138).
    arr = per_rank(base_arr)

    def f(x):
        g = lambda y: m4t.allreduce(y, op=m4t.SUM)
        (t,) = jax.linear_transpose(g, x)(x)
        return t

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, arr)


def test_allreduce_double_transpose(run_spmd, per_rank):
    # transpose(transpose(allreduce)) == allreduce.
    arr = per_rank(base_arr)

    def f(x):
        g = lambda y: m4t.allreduce(y, op=m4t.SUM)
        gt = lambda y: jax.linear_transpose(g, y)(y)[0]
        (t2,) = jax.linear_transpose(gt, x)(x)
        return t2

    out = run_spmd(f, arr)
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected)


def test_allreduce_triple_transpose(run_spmd, per_rank):
    # Reference matvec ladder goes to 3 transposes
    # (test_allreduce_matvec.py:122-179).
    arr = per_rank(base_arr)

    def f(x):
        g = lambda y: m4t.allreduce(y, op=m4t.SUM)
        gt = lambda y: jax.linear_transpose(g, y)(y)[0]
        gtt = lambda y: jax.linear_transpose(gt, y)(y)[0]
        (t3,) = jax.linear_transpose(gtt, x)(x)
        return t3

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, arr)


def test_allreduce_grad_requires_sum(run_spmd, per_rank):
    # Parity: differentiable only for SUM (reference allreduce.py:142-145).
    arr = per_rank(base_arr)
    with pytest.raises(NotImplementedError):
        run_spmd(
            lambda x: jax.grad(lambda y: m4t.allreduce(y, op=m4t.MAX).sum())(x),
            arr,
        )


# --- single-rank (eager / plain-jit) paths: the reference suite's
# --- 1-process run (SURVEY.md §4 execution model) ---


def test_allreduce_eager_world():
    # eager world: identity at size 1, arr * WORLD under the launcher
    # (every rank feeds the same data — reference oracle arr * size)
    arr = jnp.arange(6.0)
    out = m4t.allreduce(arr, op=m4t.SUM)
    np.testing.assert_allclose(out, np.arange(6.0) * WORLD)


def test_allreduce_jit_world():
    arr = jnp.arange(6.0)
    out = jax.jit(lambda x: m4t.allreduce(x, op=m4t.SUM))(arr)
    np.testing.assert_allclose(out, np.arange(6.0) * WORLD)


def test_allreduce_size1_grad():
    arr = jnp.arange(6.0)
    g = jax.grad(lambda x: m4t.allreduce(x, op=m4t.SUM).sum())(arr)
    np.testing.assert_allclose(g, np.ones(6))


def test_allreduce_rejects_bad_op():
    with pytest.raises(TypeError):
        m4t.allreduce(jnp.zeros(3), op="SUM")


def test_allreduce_rejects_token():
    with pytest.raises(TypeError):
        m4t.allreduce(jnp.zeros(3), op=m4t.SUM, token=jnp.zeros(()))
