"""Sub-communicator (Comm.Split / MPI_Comm_split analog) tests: every
collective restricted to a group must see only that group's data. The
reference supports arbitrary MPI communicators as the ``comm``
argument; groups are the SPMD equivalent, lowering to HLO
``replica_groups``."""

import numpy as np
import pytest

import jax.numpy as jnp

import mpi4jax_tpu as m4t

N = 8


def even_odd():
    # interleaved split: ranks {0,2,4,6} and {1,3,5,7}
    return m4t.Comm("ranks").Split([r % 2 for r in range(N)])


def halves():
    # contiguous split: {0..3} and {4..7}
    return m4t.Comm("ranks").Split([r // 4 for r in range(N)])


def test_split_allreduce_halves(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM, comm=comm), arr)
    np.testing.assert_allclose(out[:4], np.full(4, 0 + 1 + 2 + 3))
    np.testing.assert_allclose(out[4:], np.full(4, 4 + 5 + 6 + 7))


def test_split_allreduce_interleaved(run_spmd, per_rank):
    comm = even_odd()
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM, comm=comm), arr)
    for r in range(N):
        expected = sum(q for q in range(N) if q % 2 == r % 2)
        assert out[r] == expected


def test_split_rank_and_size(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(lambda r: np.float32(0))
    out = run_spmd(
        lambda x: x + comm.Get_rank().astype(jnp.float32) + 10 * comm.Get_size(),
        arr,
    )
    np.testing.assert_allclose(out, np.array([40, 41, 42, 43, 40, 41, 42, 43.0]))


def test_split_bcast(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(lambda r: np.arange(3, dtype=np.float32) + 10 * r)
    # root=2 is group rank 2: global rank 2 in group 0, rank 6 in group 1
    out = run_spmd(lambda x: m4t.bcast(x, 2, comm=comm), arr)
    for r in range(4):
        np.testing.assert_allclose(out[r], arr[2])
    for r in range(4, 8):
        np.testing.assert_allclose(out[r], arr[6])


def test_split_allgather(run_spmd, per_rank):
    comm = even_odd()
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allgather(x, comm=comm), arr)
    np.testing.assert_allclose(out[0], [0, 2, 4, 6])
    np.testing.assert_allclose(out[1], [1, 3, 5, 7])


def test_split_scan(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.scan(x, m4t.SUM, comm=comm), arr)
    np.testing.assert_allclose(out[:4], np.cumsum(np.arange(4.0)))
    np.testing.assert_allclose(out[4:], np.cumsum(np.arange(4.0, 8.0)))


def test_split_scatter(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(
        lambda r: (np.arange(4, dtype=np.float32) + 100 * r).reshape(4, 1)
    )
    out = run_spmd(lambda x: m4t.scatter(x, 0, comm=comm), arr)
    # group 0 root = global 0; group 1 root = global 4
    for r in range(4):
        np.testing.assert_allclose(out[r], arr[0][r])
    for r in range(4, 8):
        np.testing.assert_allclose(out[r], arr[4][r - 4])


def test_split_alltoall(run_spmd, per_rank):
    comm = halves()
    arr = per_rank(lambda r: np.arange(4, dtype=np.float32).reshape(4, 1) + 10 * r)
    out = run_spmd(lambda x: m4t.alltoall(x, comm=comm), arr)
    # within group 0: out[r][j] == arr[j][r']
    for r in range(4):
        for j in range(4):
            np.testing.assert_allclose(out[r, j], arr[j, r])
    for r in range(4, 8):
        for j in range(4):
            np.testing.assert_allclose(out[r, j], arr[4 + j, r - 4])


def test_split_sendrecv_ring(run_spmd, per_rank):
    comm = halves()
    # ring within each group, expressed in group-rank space
    dst = tuple((r + 1) % 4 for r in range(4))
    src = tuple((r - 1) % 4 for r in range(4))
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.sendrecv(x, x, src, dst, comm=comm), arr)
    np.testing.assert_allclose(out[:4], [3, 0, 1, 2])
    np.testing.assert_allclose(out[4:], [7, 4, 5, 6])


def test_split_grad(run_spmd, per_rank):
    import jax

    comm = even_odd()
    arr = per_rank(lambda r: np.float32(r + 1))
    out = run_spmd(
        lambda x: jax.grad(lambda y: m4t.allreduce(y, op=m4t.SUM, comm=comm).sum())(x),
        arr,
    )
    np.testing.assert_allclose(out, np.ones(N))


def test_split_validation(run_spmd, per_rank):
    # Unequal partitions construct fine (MPI_Comm_split parity; legal
    # on the shm backend) but are rejected when *bound* on the XLA
    # path, where HLO replica_groups must be uniform.
    uneven = m4t.GroupComm(((0, 1, 2), (3,), (4, 5, 6, 7)))
    assert not uneven.uniform
    arr = per_rank(lambda r: np.float32(r))
    with pytest.raises(ValueError, match="equal size"):
        run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM, comm=uneven), arr)
    with pytest.raises(ValueError, match="partition"):
        m4t.GroupComm(((0, 1), (1, 2)))


def test_cart_row_col_comms(run_spmd, per_rank):
    # classic pattern: row/column communicators of a 2x4 grid
    world = m4t.Comm("ranks")
    row_comm = world.Split([r // 4 for r in range(N)])
    col_comm = world.Split([r % 4 for r in range(N)])
    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        return (
            m4t.allreduce(x, op=m4t.SUM, comm=row_comm),
            m4t.allreduce(x, op=m4t.SUM, comm=col_comm),
        )

    rows, cols = run_spmd(f, arr)
    np.testing.assert_allclose(rows[:4], np.full(4, 6.0))
    np.testing.assert_allclose(rows[4:], np.full(4, 22.0))
    for r in range(N):
        np.testing.assert_allclose(cols[r], (r % 4) * 2 + 4.0)


def test_nested_split(run_spmd, per_rank):
    # GroupComm.Split: split the halves again into quarters — nested
    # MPI_Comm_split reachability (each parent group partitioned
    # independently by the global color table).
    parent = halves()                      # {0..3}, {4..7}
    child = parent.Split([r % 2 for r in range(N)])
    assert child.groups == ((0, 2), (1, 3), (4, 6), (5, 7))
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM, comm=child), arr)
    expected = {0: 2, 2: 2, 1: 4, 3: 4, 4: 10, 6: 10, 5: 12, 7: 12}
    for r in range(N):
        assert out[r] == expected[r], (r, out[r])


def test_nested_split_rank_size(run_spmd, per_rank):
    child = halves().Split([r % 2 for r in range(N)])
    arr = per_rank(lambda r: np.float32(0))
    out = run_spmd(
        lambda x: x
        + child.Get_rank().astype(jnp.float32)
        + 10.0 * child.Get_size(),
        arr,
    )
    # group rank: first member 0, second member 1; size 2 everywhere
    expected = [20, 20, 21, 21, 20, 20, 21, 21]
    np.testing.assert_allclose(out.ravel(), expected)
