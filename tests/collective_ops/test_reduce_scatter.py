"""reduce_scatter (superset op) tests: oracle, AD duality with
allgather, shm backend, split comms."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t

from tests.conftest import MY_RANK, WORLD

N = 8


def test_reduce_scatter_sum(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(N * 3, dtype=np.float32).reshape(N, 3) + r)
    out = run_spmd(lambda x: m4t.reduce_scatter(x, op=m4t.SUM), arr)
    total = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r])


def test_reduce_scatter_max(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(N, dtype=np.float32) * (-1.0) ** r)
    out = run_spmd(lambda x: m4t.reduce_scatter(x[:, None], op=m4t.MAX), arr)
    expected = np.abs(arr[0])
    np.testing.assert_allclose(out.ravel(), expected)


def test_reduce_scatter_allgather_roundtrip(run_spmd, per_rank):
    # reduce_scatter then allgather == allreduce (the ring identity)
    arr = per_rank(lambda r: np.arange(N * 2, dtype=np.float32).reshape(N, 2) * (r + 1))

    def f(x):
        return m4t.allgather(m4t.reduce_scatter(x, op=m4t.SUM))

    out = run_spmd(f, arr)
    total = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total)


def test_reduce_scatter_grad(run_spmd, per_rank):
    # transpose(reduce_scatter) = allgather: grad of sum(rs(x)) gives
    # ones in the rank's own block position on every rank
    arr = per_rank(lambda r: np.ones((N, 2), np.float32) * (r + 1))

    def f(x):
        return jax.grad(lambda y: m4t.reduce_scatter(y, op=m4t.SUM).sum())(x)

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, np.ones_like(arr))


def test_reduce_scatter_wrong_shape():
    with pytest.raises(ValueError, match="leading axis"):
        m4t.reduce_scatter(jnp.zeros((3, 2)))


def test_reduce_scatter_split(run_spmd, per_rank):
    comm = m4t.Comm("ranks").Split([r // 4 for r in range(N)])
    arr = per_rank(lambda r: np.arange(4.0, dtype=np.float32) + r)
    out = run_spmd(lambda x: m4t.reduce_scatter(x[:, None], op=m4t.SUM, comm=comm), arr)
    for r in range(N):
        grp = range(4) if r < 4 else range(4, 8)
        gr = r % 4
        expected = sum(arr[q][gr] for q in grp)
        np.testing.assert_allclose(out[r].ravel(), [expected])


def test_reduce_scatter_eager_world():
    # identical inputs on every rank: reduce = x * WORLD, this rank
    # keeps block MY_RANK
    x = jnp.arange(WORLD * 3.0).reshape(WORLD, 3)
    out = m4t.reduce_scatter(x)
    np.testing.assert_allclose(out, np.asarray(x)[MY_RANK] * WORLD)
