"""Analytic-oracle tests for allgather/alltoall/bcast/gather/reduce/
scan/scatter/barrier, mirroring the reference per-op test files
(``tests/collective_ops/test_*.py``: plain + jit + scalar variants with
rank/size-derived expected values)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4t

from tests.conftest import MY_RANK, WORLD

N = 8


# --- allgather (reference test_allgather.py) ---


def test_allgather(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(4, dtype=np.float32) + r)
    out = run_spmd(lambda x: m4t.allgather(x), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr)


def test_allgather_scalar(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.allgather(x), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], np.arange(N, dtype=np.float32))


def test_allgather_eager_world():
    out = m4t.allgather(jnp.arange(3.0))
    assert out.shape == (WORLD, 3)
    for r in range(WORLD):  # every rank feeds the same data
        np.testing.assert_allclose(out[r], np.arange(3.0))


# --- alltoall (reference test_alltoall.py) ---


def test_alltoall(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(N * 3, dtype=np.float32).reshape(N, 3) + 100 * r)
    out = run_spmd(lambda x: m4t.alltoall(x), arr)
    for r in range(N):
        for j in range(N):
            np.testing.assert_allclose(out[r, j], arr[j, r])


def test_alltoall_transposed_layout(run_spmd, per_rank):
    # Regression analog of mpi4jax#176 (reference test_alltoall.py:44-65):
    # non-contiguous input from consecutive transposes must still
    # exchange correctly.
    arr = per_rank(
        lambda r: (np.arange(N * 4, dtype=np.float32).reshape(4, N) + 10 * r)
    )

    def f(x):
        xt = jnp.transpose(x, (1, 0))
        return m4t.alltoall(xt)

    out = run_spmd(f, arr)
    for r in range(N):
        for j in range(N):
            np.testing.assert_allclose(out[r, j], arr[j].T[r])


def test_alltoall_wrong_leading_axis(run_spmd, per_rank):
    arr = per_rank(lambda r: np.zeros((3, 2), np.float32))
    with pytest.raises(ValueError):
        run_spmd(lambda x: m4t.alltoall(x), arr)


def test_alltoall_eager_world():
    # identical inputs on every rank: output block j = rank j's block
    # MY_RANK = row MY_RANK of the shared input
    x = jnp.arange(WORLD * 3.0).reshape(WORLD, 3)
    out = m4t.alltoall(x)
    expected = np.broadcast_to(np.asarray(x)[MY_RANK], (WORLD, 3))
    np.testing.assert_allclose(out, expected)


# --- bcast (reference test_bcast.py) ---


@pytest.mark.parametrize("root", [0, 3, 7])
def test_bcast(run_spmd, per_rank, root):
    arr = per_rank(lambda r: np.arange(5, dtype=np.float32) * (r + 1))
    out = run_spmd(lambda x: m4t.bcast(x, root), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[root])


def test_bcast_bool(run_spmd, per_rank):
    arr = per_rank(lambda r: np.array([r % 2 == 0, True, False]))
    out = run_spmd(lambda x: m4t.bcast(x, 3), arr)
    for r in range(N):
        np.testing.assert_array_equal(out[r], arr[3])


def test_bcast_complex(run_spmd, per_rank):
    arr = per_rank(lambda r: (np.arange(3) + 1j * r).astype(np.complex64))
    out = run_spmd(lambda x: m4t.bcast(x, 5), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[5])


def test_bcast_bad_root():
    with pytest.raises(ValueError):
        m4t.bcast(jnp.zeros(3), WORLD)  # roots are 0..WORLD-1


# --- gather (reference test_gather.py; TPU superset: all ranks get it) ---


@pytest.mark.parametrize("root", [0, 2])
def test_gather(run_spmd, per_rank, root):
    arr = per_rank(lambda r: np.arange(3, dtype=np.float32) + 10 * r)
    out = run_spmd(lambda x: m4t.gather(x, root), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr)


def test_gather_eager_world():
    out = m4t.gather(jnp.arange(3.0), 0)
    if WORLD == 1 or MY_RANK == 0:
        # root gets the (WORLD, 3) stack (shm path has exact root-only
        # semantics, reference gather.py:80-89)
        assert out.shape == (WORLD, 3)
    else:
        np.testing.assert_allclose(out, np.arange(3.0))  # x returned


# --- reduce (reference test_reduce.py) ---


@pytest.mark.parametrize("root", [0, 4])
def test_reduce(run_spmd, per_rank, root):
    arr = per_rank(lambda r: np.arange(4, dtype=np.float32) + r)
    out = run_spmd(lambda x: m4t.reduce(x, m4t.SUM, root), arr)
    for r in range(N):
        if r == root:
            np.testing.assert_allclose(out[r], arr.sum(axis=0))
        else:
            # Non-root ranks get their input back (reference
            # reduce.py:64-73).
            np.testing.assert_allclose(out[r], arr[r])


def test_reduce_max(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r * (-1) ** r))
    out = run_spmd(lambda x: m4t.reduce(x, m4t.MAX, 0), arr)
    np.testing.assert_allclose(out[0], arr.max())


# --- scan (reference test_scan.py: oracle sum(range(rank+1))) ---


def test_scan_sum(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.scan(x, m4t.SUM), arr)
    expected = np.cumsum(arr)
    np.testing.assert_allclose(out, expected)


def test_scan_array(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(4, dtype=np.float32) + r)
    out = run_spmd(lambda x: m4t.scan(x, m4t.SUM), arr)
    np.testing.assert_allclose(out, np.cumsum(arr, axis=0))


@pytest.mark.parametrize(
    "op,np_scan",
    [
        (m4t.MAX, np.maximum.accumulate),
        (m4t.MIN, np.minimum.accumulate),
        (m4t.PROD, np.multiply.accumulate),
    ],
)
def test_scan_ops(run_spmd, per_rank, op, np_scan):
    rng = np.random.RandomState(0)
    arr = np.asarray(rng.uniform(0.5, 1.5, size=(N, 3)), np.float32)
    out = run_spmd(lambda x: m4t.scan(x, op), jnp.asarray(arr))
    np.testing.assert_allclose(out, np_scan(arr, axis=0), rtol=1e-6)


def test_scan_eager_world():
    # inclusive prefix sum; every rank feeds the same data, so rank r
    # holds (r + 1) * x (reference oracle test_scan.py:16)
    x = jnp.arange(3.0)
    np.testing.assert_allclose(
        m4t.scan(x, m4t.SUM), np.arange(3.0) * (MY_RANK + 1)
    )


# --- scatter (reference test_scatter.py) ---


@pytest.mark.parametrize("root", [0, 6])
def test_scatter(run_spmd, per_rank, root):
    arr = per_rank(
        lambda r: np.arange(N * 3, dtype=np.float32).reshape(N, 3) * (r + 1)
    )
    out = run_spmd(lambda x: m4t.scatter(x, root), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[root, r])


def test_scatter_int(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(N, dtype=np.int32) * (r + 1))
    out = run_spmd(lambda x: m4t.scatter(x, 2), arr)
    np.testing.assert_array_equal(out.ravel(), arr[2])


def test_scatter_wrong_shape():
    # Root-side validation only: on the shm world a non-root rank
    # passes a free-shape block template, and calling the op there
    # would enter a real (unmatched) collective and hang the world —
    # the same reason the reference root-gates such asserts.
    if WORLD > 1 and MY_RANK != 0:
        pytest.skip("root-side shape validation (non-root passes a template)")
    with pytest.raises(ValueError):
        m4t.scatter(jnp.zeros((WORLD + 1, 2)), 0)


def test_scatter_eager_world():
    if WORLD == 1 or MY_RANK == 0:
        x = jnp.arange(WORLD * 3.0).reshape(WORLD, 3)
        out = m4t.scatter(x, 0)
        np.testing.assert_allclose(out, np.asarray(x)[MY_RANK])
    else:
        # non-root passes a block template (shm exact semantics)
        out = m4t.scatter(jnp.zeros(3), 0)
        np.testing.assert_allclose(
            out, np.arange(WORLD * 3.0).reshape(WORLD, 3)[MY_RANK]
        )


# --- barrier (reference test_barrier.py) ---


def test_barrier(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        m4t.barrier()
        return m4t.allreduce(x, op=m4t.SUM)

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, np.full(N, arr.sum()))


def test_barrier_size1():
    assert m4t.barrier() is None
