"""send / recv / sendrecv tests, mirroring the reference
``test_send_and_recv.py`` / ``test_sendrecv.py`` (ring shifts, pairwise
swaps, the deadlock-ordering pattern, transpose/grad rules)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4t

from tests.conftest import IN_LAUNCHER_WORLD, MY_RANK, WORLD

N = 8

RING_DEST = tuple((r + 1) % N for r in range(N))
RING_SRC = tuple((r - 1) % N for r in range(N))


def test_sendrecv_ring(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(3, dtype=np.float32) + 10 * r)
    out = run_spmd(
        lambda x: m4t.sendrecv(x, x, RING_SRC, RING_DEST), arr
    )
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[(r - 1) % N])


def test_sendrecv_swap(run_spmd, per_rank):
    # Pairwise exchange: reference test_sendrecv.py:13-40 pattern
    # (rank 0 <-> rank 1 etc.).
    partner = tuple(r + 1 if r % 2 == 0 else r - 1 for r in range(N))
    arr = per_rank(lambda r: np.float32(r))
    out = run_spmd(lambda x: m4t.sendrecv(x, x, partner, partner), arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[partner[r]])


def test_sendrecv_proc_null_keeps_template(run_spmd, per_rank):
    # Open-boundary shift: rank 0 receives nothing and keeps its
    # template (MPI_PROC_NULL recv semantics).
    dest = tuple(r + 1 if r < N - 1 else m4t.PROC_NULL for r in range(N))
    src = tuple(r - 1 if r > 0 else m4t.PROC_NULL for r in range(N))
    arr = per_rank(lambda r: np.float32(r + 1))

    def f(x):
        template = jnp.full_like(x, -99.0)
        return m4t.sendrecv(x, template, src, dest)

    out = run_spmd(f, arr)
    assert out[0] == -99.0
    for r in range(1, N):
        np.testing.assert_allclose(out[r], arr[r - 1])


def test_sendrecv_grad(run_spmd, per_rank):
    # Transpose swaps source and dest (reference sendrecv.py:278-293):
    # grad of sum(sendrecv ring-shift) routes cotangents backwards,
    # giving ones everywhere for a full ring.
    arr = per_rank(lambda r: np.float32(r + 1))

    def f(x):
        return jax.grad(lambda y: m4t.sendrecv(y, y, RING_SRC, RING_DEST).sum())(x)

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, np.ones(N))


def test_sendrecv_transpose_inverts_ring(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))

    def shift(y):
        return m4t.sendrecv(y, jnp.zeros_like(y), RING_SRC, RING_DEST)

    def f(x):
        (t,) = jax.linear_transpose(shift, x)(x)
        return t

    out = run_spmd(f, arr)
    # forward shifts +1, transpose shifts -1.
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[(r + 1) % N])


def test_sendrecv_jvp_supported(run_spmd, per_rank):
    # Improvement over the reference (which raises for jacfwd,
    # sendrecv.py:122-127): forward-mode works on the HLO path.
    arr = per_rank(lambda r: np.float32(r + 1))

    def f(x):
        p, t = jax.jvp(
            lambda y: m4t.sendrecv(y, y, RING_SRC, RING_DEST), (x,), (2.0 * x,)
        )
        return p, t

    p, t = run_spmd(f, arr)
    for r in range(N):
        np.testing.assert_allclose(p[r], arr[(r - 1) % N])
        np.testing.assert_allclose(t[r], 2 * arr[(r - 1) % N])


def test_sendrecv_vmap(run_spmd, per_rank):
    arr = per_rank(lambda r: np.arange(4, dtype=np.float32) + 10 * r)

    def f(x):
        return jax.vmap(lambda y: m4t.sendrecv(y, y, RING_SRC, RING_DEST))(x)

    out = run_spmd(f, arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[(r - 1) % N])


def test_send_recv_pair(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r + 1))

    def f(x):
        m4t.send(x, RING_DEST, tag=7)
        return m4t.recv(jnp.zeros_like(x), RING_SRC, tag=7)

    out = run_spmd(f, arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[(r - 1) % N])


def test_send_recv_two_channels_ordered(run_spmd, per_rank):
    # The deadlock-regression analog (reference
    # test_send_and_recv.py:91-110): two in-flight transfers in one
    # program, matched by tag, must both deliver.
    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        m4t.send(x, RING_DEST, tag=1)          # +1 ring
        m4t.send(x * 10, RING_SRC, tag=2)      # -1 ring
        a = m4t.recv(jnp.zeros_like(x), RING_SRC, tag=1)
        b = m4t.recv(jnp.zeros_like(x), RING_DEST, tag=2)
        return a, b

    a, b = run_spmd(f, arr)
    for r in range(N):
        np.testing.assert_allclose(a[r], arr[(r - 1) % N])
        np.testing.assert_allclose(b[r], 10 * arr[(r + 1) % N])


def test_send_recv_any_tag(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        m4t.send(x, RING_DEST, tag=42)
        return m4t.recv(jnp.zeros_like(x), RING_SRC)  # ANY_TAG

    out = run_spmd(f, arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], arr[(r - 1) % N])


def test_recv_without_send_raises(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))
    with pytest.raises(Exception, match="no matching send"):
        run_spmd(lambda x: m4t.recv(x, RING_SRC, tag=99), arr)


def test_send_edge_validation():
    with pytest.raises(ValueError, match="out of range"):
        m4t.send(jnp.zeros(3), (WORLD + 3,) * WORLD)


def test_sendrecv_mismatched_tables(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))
    bad_src = tuple((r + 1) % N for r in range(N))  # should be -1 ring
    with pytest.raises(ValueError, match="mirror"):
        run_spmd(lambda x: m4t.sendrecv(x, x, bad_src, RING_DEST), arr)


@pytest.mark.skipif(
    IN_LAUNCHER_WORLD,
    reason="tests the XLA path's Status/ANY_SOURCE rejections; the shm "
    "world supports both (tested in test_shm_backend.py)",
)
def test_sendrecv_status_contract():
    # wrong type is a TypeError; a real Status raises on the XLA path
    # (no HLO analog — supported on the shm backend only, see
    # tests/test_shm_backend.py::test_status_and_any_source)
    with pytest.raises(TypeError, match="Status"):
        m4t.sendrecv(
            jnp.zeros(3), jnp.zeros(3), (0,), (0,), status=object()
        )
    with pytest.raises(NotImplementedError, match="shm"):
        m4t.sendrecv(
            jnp.zeros(3), jnp.zeros(3), (0,), (0,), status=m4t.Status()
        )
    with pytest.raises(NotImplementedError, match="shm"):
        m4t.recv(jnp.zeros(3), (0,), status=m4t.Status())
    with pytest.raises(NotImplementedError, match="ANY_SOURCE"):
        m4t.recv(jnp.zeros(3), m4t.ANY_SOURCE)


def test_sendrecv_self_edges():
    # every rank exchanges with itself: identity at any world size
    idx = tuple(range(WORLD))
    x = jnp.arange(3.0)
    out = m4t.sendrecv(x, jnp.zeros_like(x), idx, idx)
    np.testing.assert_allclose(out, x)


def test_user_tag_validation():
    # ANY_TAG is receive-side only; other negatives are invalid (MPI
    # parity). The reserved namespace >= 1<<20 applies to the shm
    # backend only (group-collective internals, ops/p2p.py
    # check_user_tag); on the XLA path tags are trace-time metadata and
    # MPI_TAG_UB-style large tags must keep working.
    x = jnp.ones(3)
    src = (0,) * WORLD if WORLD > 1 else 0
    with pytest.raises(ValueError, match="receive side"):
        m4t.sendrecv(x, x, source=src, dest=src, sendtag=m4t.ANY_TAG)
    with pytest.raises(ValueError, match="negative tags"):
        m4t.recv(x, source=src, tag=-7)
    big = (1 << 20) + 5
    idx = tuple(range(WORLD))
    if WORLD == 1:
        out = m4t.sendrecv(
            x, jnp.zeros_like(x), idx, idx, sendtag=big, recvtag=big
        )
        np.testing.assert_allclose(out, x)
    else:
        with pytest.raises(ValueError, match="reserved"):
            m4t.sendrecv(
                x, jnp.zeros_like(x), idx, idx, sendtag=big, recvtag=big
            )


def test_foreign_negative_sentinel_rejected_in_tables():
    # mpi4py's numeric sentinels vary by MPI build (-2 is ANY_SOURCE on
    # MPICH, PROC_NULL on OpenMPI); table entries below -1 must fail
    # loudly instead of silently acting as PROC_NULL.
    import jax.numpy as jnp
    import pytest

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu import get_default_comm

    x = jnp.ones(3)
    n = get_default_comm().Get_size()
    with pytest.raises(ValueError, match="PROC_NULL"):
        m4t.send(x, dest=(-2,) * n)
