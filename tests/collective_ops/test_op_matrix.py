"""Cross-product sweep: every collective x dtype x shape against a
numpy oracle computed from the stacked per-rank inputs. The reference
covers this per-op with hand-written cases (SURVEY.md §4 technique 2);
this sweep is the dense version of that matrix, catching dtype- or
shape-specific lowering regressions the targeted tests miss."""

import numpy as np
import pytest

import jax.numpy as jnp

import mpi4jax_tpu as m4t

N = 8

DTYPES = [np.float32, np.float64, np.int32, np.int64, np.uint8, np.bool_]
SHAPES = [(), (5,), (3, 4)]


def _inputs(dtype, shape, rng):
    if dtype == np.bool_:
        return (rng.rand(N, *shape) > 0.4).astype(dtype)
    if np.issubdtype(dtype, np.integer):
        return rng.randint(1, 5, size=(N,) + shape).astype(dtype)
    return rng.rand(N, *shape).astype(dtype) + 0.5


def _tol(dtype):
    # the harness runs with jax_enable_x64=False (conftest), so f64
    # inputs execute in f32 — tolerances follow the *effective* dtype
    if np.issubdtype(dtype, np.floating):
        return dict(rtol=1e-5, atol=1e-6)
    return dict(rtol=0)


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("shape", SHAPES, ids=str)
def test_allreduce_sum_matrix(run_spmd, dtype, shape):
    rng = np.random.RandomState(0)
    arr = _inputs(dtype, shape, rng)
    out = run_spmd(lambda x: m4t.allreduce(x, op=m4t.SUM), jnp.asarray(arr))
    if dtype == np.bool_:
        expected = arr.any(axis=0)  # bool SUM == logical OR (via int32)
        assert (np.asarray(out[0]) != 0).astype(bool).tolist() == expected.tolist()
        return
    expected = arr.sum(axis=0, dtype=dtype)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, **_tol(dtype))


@pytest.mark.parametrize("op,oracle", [
    (m4t.MAX, lambda a: a.max(axis=0)),
    (m4t.MIN, lambda a: a.min(axis=0)),
    (m4t.PROD, lambda a: a.prod(axis=0)),
], ids=["max", "min", "prod"])
@pytest.mark.parametrize("dtype", [np.float32, np.int32], ids=["f32", "i32"])
def test_allreduce_ops_matrix(run_spmd, op, oracle, dtype):
    rng = np.random.RandomState(1)
    arr = _inputs(dtype, (4,), rng)
    out = run_spmd(lambda x: m4t.allreduce(x, op=op), jnp.asarray(arr))
    expected = oracle(arr)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, **_tol(dtype))


@pytest.mark.parametrize("op,oracle", [
    (m4t.BAND, lambda a: np.bitwise_and.reduce(a, axis=0)),
    (m4t.BOR, lambda a: np.bitwise_or.reduce(a, axis=0)),
    (m4t.BXOR, lambda a: np.bitwise_xor.reduce(a, axis=0)),
    (m4t.LAND, lambda a: (a != 0).all(axis=0)),
    (m4t.LOR, lambda a: (a != 0).any(axis=0)),
    (m4t.LXOR, lambda a: ((a != 0).sum(axis=0) % 2).astype(bool)),
], ids=["band", "bor", "bxor", "land", "lor", "lxor"])
def test_allreduce_bitlogic_matrix(run_spmd, op, oracle):
    rng = np.random.RandomState(2)
    arr = rng.randint(0, 4, size=(N, 6)).astype(np.int32)
    out = run_spmd(lambda x: m4t.allreduce(x, op=op), jnp.asarray(arr))
    expected = oracle(arr).astype(np.int32)
    for r in range(N):
        np.testing.assert_array_equal(
            (np.asarray(out[r]) != 0).astype(np.int32)
            if op in (m4t.LAND, m4t.LOR, m4t.LXOR)
            else np.asarray(out[r]),
            (expected != 0).astype(np.int32)
            if op in (m4t.LAND, m4t.LOR, m4t.LXOR)
            else expected,
        )


@pytest.mark.parametrize("dtype", [np.float32, np.int32, np.bool_],
                         ids=["f32", "i32", "bool"])
def test_moving_ops_matrix(run_spmd, dtype):
    """allgather / alltoall / bcast / scatter / gather move bytes
    without interpreting them — any dtype must round-trip exactly."""
    rng = np.random.RandomState(3)
    arr = _inputs(dtype, (N, 2), rng)  # (N ranks, N blocks, 2)

    def f(x):
        ag = m4t.allgather(x[0])          # (N, 2)
        a2a = m4t.alltoall(x)             # (N, 2)
        bc = m4t.bcast(x[0], 2)
        sc = m4t.scatter(x, 3)
        ga = m4t.gather(x[0], 1)
        return ag, a2a, bc, sc, ga

    ag, a2a, bc, sc, ga = run_spmd(f, jnp.asarray(arr))
    for r in range(N):
        np.testing.assert_array_equal(ag[r], arr[:, 0])       # stacked firsts
        np.testing.assert_array_equal(a2a[r], arr[:, r])      # transposed blocks
        np.testing.assert_array_equal(bc[r], arr[2, 0])       # root 2's block
        np.testing.assert_array_equal(sc[r], arr[3, r])       # root 3's row r
        np.testing.assert_array_equal(ga[r], arr[:, 0])       # gather = stacked


@pytest.mark.parametrize("dtype", [np.float32, np.int64], ids=["f32", "i64"])
def test_scan_matrix(run_spmd, dtype):
    rng = np.random.RandomState(4)
    arr = _inputs(dtype, (3,), rng)
    out = run_spmd(lambda x: m4t.scan(x, m4t.SUM), jnp.asarray(arr))
    running = np.cumsum(arr.astype(np.float64), axis=0)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r], np.float64), running[r], rtol=1e-5
        )


def test_inputs_never_mutated(run_spmd):
    # the reference asserts inputs are preserved everywhere
    # (test_allreduce.py:17-21 _arr copies); sweep it across ops here
    rng = np.random.RandomState(5)
    arr = rng.rand(N, N, 2).astype(np.float32)
    arr_copy = arr.copy()

    def f(x):
        m4t.allreduce(x[0], op=m4t.SUM)
        m4t.alltoall(x)
        m4t.scan(x[0], m4t.SUM)
        return x

    out = run_spmd(f, jnp.asarray(arr))
    np.testing.assert_array_equal(arr, arr_copy)
    np.testing.assert_array_equal(out, arr_copy)
