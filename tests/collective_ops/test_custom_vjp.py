"""netket-style custom_vjp expectation pattern through allreduce —
the reference's hardest AD acceptance test
(``tests/collective_ops/test_allreduce.py:252-322``): a distributed
Monte-Carlo-style expectation whose custom VJP internally uses
allreduce, composed under jit + grad, must give per-rank-correct
gradients identical to the single-process computation."""

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t

N = 8
K = 4  # samples per rank


def make_expect(n_total):
    """<f> = (1/n_total) sum over ALL samples of f(w, x), distributed:
    each rank holds K samples; forward and backward both communicate
    through allreduce with custom_vjp stitching them together."""

    @jax.custom_vjp
    def expect(w, xs):
        return _expect_fwd(w, xs)[0]

    def _local_f(w, xs):
        return jnp.sin(xs @ w)  # (K,)

    def _expect_fwd(w, xs):
        local = _local_f(w, xs)
        mean = m4t.allreduce(local.sum(), op=m4t.SUM) / n_total
        return mean, (w, xs)

    def _expect_bwd(res, ct):
        w, xs = res
        # d<f>/dw = (1/n) sum_all d f_i/dw: local piece then allreduce
        _, vjp = jax.vjp(lambda w_: _local_f(w_, xs).sum(), w)
        (local_grad,) = vjp(ct / n_total)
        grad = m4t.allreduce(local_grad, op=m4t.SUM)
        return grad, jnp.zeros_like(xs)

    expect.defvjp(_expect_fwd, _expect_bwd)
    return expect


def test_custom_vjp_expectation(run_spmd):
    rng = np.random.RandomState(0)
    dim = 5
    w = rng.randn(dim).astype(np.float32)
    xs_all = rng.randn(N * K, dim).astype(np.float32)

    expect = make_expect(N * K)

    def distributed(w_loc, xs_loc):
        val, grad = jax.value_and_grad(lambda ww: expect(ww, xs_loc))(w_loc)
        return val * jnp.ones(()), grad

    w_stack = np.tile(w, (N, 1))
    xs_stack = xs_all.reshape(N, K, dim)
    val, grad = run_spmd(distributed, jnp.asarray(w_stack), jnp.asarray(xs_stack))

    # single-process ground truth
    def full(ww):
        return jnp.sin(jnp.asarray(xs_all) @ ww).mean()

    v_ref, g_ref = jax.value_and_grad(full)(jnp.asarray(w))
    np.testing.assert_allclose(val, np.full(N, float(v_ref)), rtol=1e-5)
    for r in range(N):
        np.testing.assert_allclose(grad[r], np.asarray(g_ref), rtol=1e-4)


def test_custom_vjp_under_jit_and_scan(run_spmd):
    """The reference additionally composes this with lax control flow
    (``tests/test_jax_transforms.py``): run the expectation gradient
    inside a scan loop (mini SGD) and check it descends."""
    rng = np.random.RandomState(1)
    dim = 4
    w = rng.randn(dim).astype(np.float32)
    xs_all = rng.randn(N * K, dim).astype(np.float32)
    expect = make_expect(N * K)

    def train(w_loc, xs_loc):
        def body(w_c, _):
            g = jax.grad(lambda ww: expect(ww, xs_loc) ** 2)(w_c)
            return w_c - 0.5 * g, expect(w_c, xs_loc) ** 2

        w_final, losses = jax.lax.scan(body, w_loc, None, length=5)
        return w_final, losses

    w_stack = np.tile(w, (N, 1))
    xs_stack = xs_all.reshape(N, K, dim)
    w_final, losses = run_spmd(train, jnp.asarray(w_stack), jnp.asarray(xs_stack))
    # replicated across ranks, and loss decreasing
    np.testing.assert_allclose(w_final[0], w_final[5], rtol=1e-5)
    assert losses[0][-1] < losses[0][0]
