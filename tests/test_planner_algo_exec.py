"""Verified-algorithm execution (planner/algo.py execute_spmd): the
numerical parity matrix of the three shipped algorithms against the
HLO collective across world sizes x dtypes on the host mesh, dispatch
routing through ``algo:*`` pins and armed plans, and the telemetry
impl stamp — the on-device half of tests/test_planner_algo.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu import config, observability as obs
from mpi4jax_tpu.parallel import spmd, world_mesh
from mpi4jax_tpu.planner import algo as algomod
from mpi4jax_tpu.planner import dispatch, plan as planmod

pytestmark = [pytest.mark.tuning, pytest.mark.algo]

_WORLDS = (2, 4, 8)
_DTYPES = ("float32", "bfloat16")


def _tag(stem):
    import os

    return algomod.load(
        os.path.join(algomod.algos_dir(), stem + ".json")
    ).tag


@pytest.fixture(autouse=True)
def _clean_dispatch(monkeypatch):
    monkeypatch.setattr(config, "PLATFORM_CLASS", "cpu")
    saved = (dispatch.active, dict(dispatch.pins))
    dispatch.disarm()
    dispatch.pins = {}
    yield
    dispatch.active, dispatch.pins = saved
    obs.disable()
    obs.reset()


def _payload(world, dtype, seed=0):
    rng = np.random.RandomState(seed)
    # 777 elements: deliberately unaligned to every chunk/slot size
    return rng.randn(world, 777).astype(np.float32) * 4.0


def _run_allreduce(world, arr, dtype):
    fn = spmd(lambda x: m4t.allreduce(x), mesh=world_mesh(world))
    return np.asarray(
        fn(jnp.asarray(arr).astype(dtype)).astype(jnp.float32)
    )


def _run_alltoall(world, arr, dtype):
    fn = spmd(lambda x: m4t.alltoall(x), mesh=world_mesh(world))
    out = fn(jnp.asarray(arr).astype(dtype))
    return np.asarray(out.astype(jnp.float32))


# ---------------------------------------------------------------------
# numerical parity: verified algorithms vs the HLO collective
# ---------------------------------------------------------------------


@pytest.mark.parametrize("world", _WORLDS)
@pytest.mark.parametrize("dtype", _DTYPES)
@pytest.mark.parametrize("stem", ("ring", "recursive_double"))
def test_allreduce_algo_parity(world, dtype, stem):
    """Acceptance: each verified AllReduce algorithm matches the exact
    reduction (and the HLO route) at every proven world x dtype."""
    arr = _payload(world, dtype)
    baseline = _run_allreduce(world, arr, dtype)  # unarmed -> hlo
    dispatch.set_pins(f"AllReduce:{_tag(stem)}")
    out = _run_allreduce(world, arr, dtype)
    exact = arr.sum(axis=0)
    scale = max(np.abs(exact).max(), 1e-6)
    tol = 0.02 if dtype == "bfloat16" else 1e-5
    for r in range(world):
        err = np.abs(out[r] - exact).max() / scale
        assert err < tol, (stem, world, dtype, r, err)
        berr = np.abs(baseline[r] - exact).max() / scale
        assert berr < tol  # the comparison itself is honest


@pytest.mark.parametrize("world", _WORLDS)
@pytest.mark.parametrize("dtype", _DTYPES)
def test_alltoall_twophase_parity(world, dtype):
    """The two-phase alltoall is pure data movement: its output must
    be bit-identical to the HLO route at every proven world x dtype."""
    rng = np.random.RandomState(3)
    # per-rank block layout: leading axis = communicator size
    arr = rng.randn(world, world, 5).astype(np.float32)
    baseline = _run_alltoall(world, arr, dtype)
    dispatch.set_pins(f"AllToAll:{_tag('alltoall_twophase')}")
    out = _run_alltoall(world, arr, dtype)
    np.testing.assert_array_equal(out, baseline)


def test_allreduce_algo_parity_shifted_inputs():
    """Regression guard for slot bookkeeping: a payload whose value
    depends on the rank index catches any chunk-routing permutation
    the symmetric random payload could mask."""
    world = 4
    arr = np.arange(world * 64, dtype=np.float32).reshape(world, 64)
    dispatch.set_pins(f"AllReduce:{_tag('ring')}")
    out = _run_allreduce(world, arr, "float32")
    exact = arr.sum(axis=0)
    for r in range(world):
        np.testing.assert_allclose(out[r], exact, rtol=1e-6)


# ---------------------------------------------------------------------
# dispatch integration: armed plan routing + telemetry stamp
# ---------------------------------------------------------------------


def test_armed_plan_routes_through_algo_impl(tmp_path):
    """A plan cache entry naming an algo impl routes the emission and
    stamps the decision on telemetry — sweepable on equal footing."""
    world = 4
    tag = _tag("ring")
    arr = _payload(world, "float32")
    key = planmod.plan_key(
        "AllReduce", nbytes=arr[0].nbytes, dtype="float32",
        world=world, axes=("ranks",), platform="cpu",
    )
    p = planmod.Plan(platform="cpu")
    p.entries[key] = planmod.PlanEntry(impl=tag, source="analytic")
    dispatch.arm(p)
    obs.enable()
    out = _run_allreduce(world, arr, "float32")
    exact = arr.sum(axis=0)
    for r in range(world):
        np.testing.assert_allclose(out[r], exact, rtol=1e-5)
    emissions = obs.snapshot()["emissions"]
    armed = [e for e in emissions if e.get("impl") == tag]
    assert armed, [e.get("impl") for e in emissions]


def test_pin_to_unproven_world_falls_back():
    """Pinning an algo impl at a world outside its proof set must not
    mis-route: the seam falls back to a feasible impl and the answer
    stays exact (the pin is advisory, the proof is the contract)."""
    tag = _tag("ring")
    spec = algomod.get(tag)
    assert spec is not None and 3 not in spec.per_world
    world = 3
    arr = _payload(world, "float32")
    dispatch.set_pins(f"AllReduce:{tag}")
    out = _run_allreduce(world, arr, "float32")
    exact = arr.sum(axis=0)
    for r in range(world):
        np.testing.assert_allclose(out[r], exact, rtol=1e-5)
