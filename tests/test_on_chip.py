"""Opt-in smoke tests against the real accelerator (TPU) chip.

The main suite pins the CPU platform (``conftest.py``), mirroring the
reference's default single-rank CI leg. This module is the on-chip
leg: each test launches a subprocess *without* the CPU forcing so the
container's accelerator plugin resolves, probes the chip with a hard
wall-clock timeout (the tunnel can wedge inside PJRT init where no
Python signal handler runs — only a process kill works, see
``bench.py``), and skips cleanly when no healthy chip answers. This
keeps the suite green on CPU-only CI while recording real-hardware
coverage whenever the chip is reachable.

Covered on-chip: the README allreduce flow (eager + jit + grad), the
token-ordered sendrecv/alltoall pipeline at world size 1, and the
fused Pallas solver step (``models/fused_step.py``) checked against
the XLA step on a small grid — the compiled Mosaic path, not
interpret mode.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: generous: first compile on the chip is ~20-40 s
TIMEOUT_S = int(os.environ.get("M4T_ONCHIP_TEST_TIMEOUT", "240"))

_PROBE = """
import jax
d = jax.devices()
assert d and d[0].platform != "cpu", f"no accelerator: {d}"
print("ok")
"""


def _run(src: str, timeout: int = TIMEOUT_S):
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    proc = subprocess.Popen(
        [sys.executable, "-c", src],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.communicate()
        return None, "", "timeout"
    return proc.returncode, out, err


_CHIP_STATE = {}


def _chip_available() -> bool:
    """Memoized probe, run at first test setup — NOT at import, so
    collecting the suite (or running unrelated tests) never pays the
    probe subprocess or its 90 s wedge timeout."""
    if "ok" not in _CHIP_STATE:
        if os.environ.get("M4T_SKIP_ONCHIP", "0") != "0":
            _CHIP_STATE["ok"] = False
        else:
            rc, out, _ = _run(_PROBE, timeout=90)
            _CHIP_STATE["ok"] = rc == 0 and "ok" in out
    return _CHIP_STATE["ok"]


@pytest.fixture()
def chip():
    if not _chip_available():
        pytest.skip("no healthy accelerator chip reachable")


def test_readme_allreduce_on_chip(chip):
    rc, out, err = _run("""
import jax, jax.numpy as jnp
import mpi4jax_tpu as m4t

x = jnp.ones((3, 3))
eager = m4t.allreduce(x, op=m4t.SUM)
jitted = jax.jit(lambda a: m4t.allreduce(a, op=m4t.SUM))(x)
assert float(eager.sum()) == 9.0 and float(jitted.sum()) == 9.0
g = jax.grad(lambda a: m4t.allreduce(a, op=m4t.SUM).sum())(x)
assert float(g[0, 0]) == 1.0  # transpose of SUM-allreduce = identity
print("PASS", jax.devices()[0])
""")
    assert rc == 0 and "PASS" in out, (rc, out, err[-1500:])


def test_token_pipeline_on_chip(chip):
    rc, out, err = _run("""
import jax, jax.numpy as jnp
import mpi4jax_tpu as m4t

n = 1  # world size on the single exposed chip; ring tables degenerate
ring = tuple((r + 1) % n for r in range(n))

@jax.jit
def pipeline(x):
    y = m4t.alltoall(x)
    y = m4t.sendrecv(y, y, source=ring, dest=ring, sendtag=7)
    return m4t.allreduce(y, op=m4t.SUM)

out = pipeline(jnp.arange(4.0).reshape(1, 4))
assert out.shape == (1, 4)
assert float(out.sum()) == 6.0
print("PASS")
""")
    assert rc == 0 and "PASS" in out, (rc, out, err[-1500:])


def test_fused_step_compiled_on_chip(chip):
    """Compiled Mosaic fused step vs XLA step on the real chip."""
    rc, out, err = _run("""
import jax, jax.numpy as jnp
from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models import fused_step as fs

cfg = ShallowWaterConfig(nx=48, ny=30, dims=(1, 1))
model = ShallowWaterModel(cfg)
state = ModelState(*(jnp.asarray(b[0]) for b in model.initial_state_blocks()))
ref = model.step(state, first_step=True)
cur = fs.pad_state(cfg, ref, 8)
worst = 0.0
for _ in range(4):
    ref = model.step(ref)
    cur = fs.fused_step(cfg, cur, block_rows=8, interpret=False)
    got = fs.crop_state(cfg, cur)
    for a, b in zip(ref, got):
        d = float(jnp.max(jnp.abs(a - b)))
        worst = max(worst, d / (1.0 + float(jnp.max(jnp.abs(a)))))
assert worst < 1e-5, worst
print(f"PASS worst={worst:.2e}")
""")
    assert rc == 0 and "PASS" in out, (rc, out, err[-1500:])


def test_fused_spmd_kernel_compiled_on_chip(chip):
    """Compiled deep-halo SPMD kernel (SMEM rank offset + PROC_NULL
    exchange) at the chip's world size of 1, vs the XLA step."""
    rc, out, err = _run("""
import jax, jax.numpy as jnp
import numpy as np
from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models.fused_spmd import FusedRowDecomp

cfg = ShallowWaterConfig(nx=48, ny=96, dims=(1, 1))
model = ShallowWaterModel(cfg)
state = ModelState(*(jnp.asarray(b[0]) for b in model.initial_state_blocks()))
s1 = model.step(state, first_step=True)
stepper = FusedRowDecomp(cfg, block_rows=8, interpret=False)
fus = jax.jit(lambda s: stepper.multistep(s, 4))(s1)
ref = s1
for _ in range(4):
    ref = model.step(ref)
worst = 0.0
for a, b in zip(ref, fus):
    ai = np.asarray(a)[1:-1, 1:-1]; bi = np.asarray(b)[1:-1, 1:-1]
    worst = max(worst, np.max(np.abs(ai - bi)) / (1.0 + np.max(np.abs(ai))))
assert worst < 1e-5, worst
print(f"PASS worst={worst:.2e}")
""")
    assert rc == 0 and "PASS" in out, (rc, out, err[-1500:])


def test_fused_temporal_blocking_compiled_on_chip(chip):
    """Compiled Mosaic temporally blocked passes (steps_per_pass=2 at
    halo 8 and =4 at halo 16) vs the XLA trajectory on the real chip —
    the hot-loop variants bench.py's routing ladder prefers."""
    rc, out, err = _run("""
import jax, jax.numpy as jnp
from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models import fused_step as fs

cfg = ShallowWaterConfig(nx=48, ny=64, dims=(1, 1))
model = ShallowWaterModel(cfg)
state = ModelState(*(jnp.asarray(b[0]) for b in model.initial_state_blocks()))
s1 = model.step(state, first_step=True)
ref = s1
for _ in range(4):
    ref = model.step(ref)
for spp in (2, 4):
    b = 16
    fus = fs.crop_state(cfg, fs.fused_multistep(
        cfg, fs.pad_state(cfg, s1, b), 4, block_rows=b,
        interpret=False, steps_per_pass=spp))
    worst = 0.0
    for a, g in zip(ref, fus):
        d = float(jnp.max(jnp.abs(a - g)))
        worst = max(worst, d / (1.0 + float(jnp.max(jnp.abs(a)))))
    assert worst < 1e-5, (spp, worst)
    print(f"spp={spp} worst={worst:.2e}")
print("PASS")
""")
    assert rc == 0 and "PASS" in out, (rc, out, err[-1500:])
