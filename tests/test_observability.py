"""Comm telemetry subsystem tests (``mpi4jax_tpu/observability/``).

Covers the ISSUE-1 acceptance surface:

- counters increment per bind with correct byte accounting across
  dtypes;
- ``snapshot()`` / ``reset()`` semantics (snapshots are detached
  copies);
- JSONL event schema round-trips and matches the probe-log shape
  (``ts`` in ``%Y-%m-%dT%H:%M:%SZ``, one JSON object per line);
- the registry is zero-overhead when disabled: no host callbacks in
  the traced program, no records accumulated;
- the emission correlation id is shared across the debug log line,
  the metric record, and the profiler annotation name.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4t
from mpi4jax_tpu import observability as obs
from mpi4jax_tpu.observability import events
from mpi4jax_tpu.observability.metrics import MetricsRegistry, Reservoir

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Each test starts disabled with an empty registry and no sink,
    and leaves no global telemetry state behind."""
    from mpi4jax_tpu.observability import metrics as metrics_mod

    prev_enabled = metrics_mod._enabled
    prev_runtime = metrics_mod._runtime_enabled
    prev_sink = events.get_sink()
    obs.reset()
    obs.disable()
    metrics_mod._runtime_enabled = False
    events.set_sink(None)
    yield
    obs.reset()
    metrics_mod._enabled = prev_enabled
    metrics_mod._runtime_enabled = prev_runtime
    events._sink = prev_sink


# ---------------------------------------------------------------------------
# smoke / CI guard
# ---------------------------------------------------------------------------


def test_import_smoke_and_disabled_by_default():
    """Tier-1-safe smoke: the subsystem imports under JAX_PLATFORMS=cpu
    and is inert unless explicitly enabled."""
    import mpi4jax_tpu.observability  # noqa: F401

    assert obs.enabled() is False
    assert obs.runtime_enabled() is False
    m4t.allreduce(jnp.ones(3))
    snap = obs.snapshot()
    assert snap["totals"]["emissions"] == 0
    assert snap["ops"] == {}


# ---------------------------------------------------------------------------
# counters and byte accounting
# ---------------------------------------------------------------------------


def test_counters_increment_per_bind_with_byte_accounting():
    obs.enable()
    m4t.allreduce(jnp.ones((4, 2), jnp.float32))  # 8 * 4 B
    m4t.allreduce(jnp.ones(16, jnp.float32))      # 16 * 4 B
    m4t.allgather(jnp.ones(3, jnp.int8))          # 3 * 1 B
    m4t.bcast(jnp.ones(5, jnp.float16), 0)        # 5 * 2 B

    snap = obs.snapshot()
    ar = snap["ops"]["AllReduce"]
    assert ar["emissions"] == 2
    assert ar["payload_bytes"] == 8 * 4 + 16 * 4
    assert ar["by_dtype"]["float32"] == [2, 96]
    ag = snap["ops"]["AllGather"]
    assert ag["emissions"] == 1 and ag["payload_bytes"] == 3
    bc = snap["ops"]["Bcast"]
    assert bc["emissions"] == 1 and bc["payload_bytes"] == 10
    assert snap["totals"]["emissions"] == 4
    assert snap["totals"]["payload_bytes"] == 32 + 64 + 3 + 10


def test_dtype_breakdown_across_mixed_dtypes():
    obs.enable()
    m4t.allreduce(jnp.ones(8, jnp.float32))
    m4t.allreduce(jnp.ones(8, jnp.bfloat16))
    by_dtype = obs.snapshot()["ops"]["AllReduce"]["by_dtype"]
    assert by_dtype["float32"] == [1, 32]
    assert by_dtype["bfloat16"] == [1, 16]


def test_barrier_counts_zero_payload():
    obs.enable()
    m4t.barrier()
    b = obs.snapshot()["ops"]["Barrier"]
    assert b["emissions"] == 1
    assert b["payload_bytes"] == 0


def test_every_collective_wrapper_records(run_spmd, per_rank):
    """One pass over the non-root collective family under the 8-rank
    mesh: every op shows up in the registry under its own name."""
    obs.enable()

    def step(x):
        y = m4t.allreduce(x)
        y = m4t.allgather(y)[0]
        z = m4t.alltoall(jnp.broadcast_to(y, (8,) + y.shape))
        w = m4t.reduce_scatter(jnp.broadcast_to(y, (8,) + y.shape))
        s = m4t.scan(x)
        m4t.barrier()
        return y + z[0] + w + s

    run_spmd(step, np.ones((8, 4), np.float32))
    ops = obs.snapshot()["ops"]
    for name in (
        "AllReduce", "AllGather", "AllToAll", "ReduceScatter", "Scan",
        "Barrier",
    ):
        assert ops[name]["emissions"] >= 1, name
        assert ops[name]["by_axes"].get("ranks", 0) >= 1, name


def test_mesh_axes_recorded(run_spmd):
    obs.enable()
    run_spmd(lambda x: m4t.allreduce(x), np.ones((8, 4), np.float32))
    ar = obs.snapshot()["ops"]["AllReduce"]
    assert ar["by_axes"] == {"ranks": 1}
    # per-rank payload: 4 f32 items
    assert ar["payload_bytes"] == 16


def test_quantized_allreduce_recorded(run_spmd):
    obs.enable()
    out = run_spmd(
        lambda x: m4t.quantized_allreduce(x),
        np.ones((8, 512), np.float32),
    )
    assert np.allclose(out[0], 8.0, atol=0.2)
    q = obs.snapshot()["ops"]["QuantizedAllReduce"]
    assert q["emissions"] == 1
    assert q["payload_bytes"] == 512 * 4


# ---------------------------------------------------------------------------
# snapshot / reset semantics
# ---------------------------------------------------------------------------


def test_snapshot_is_detached_copy():
    obs.enable()
    m4t.allreduce(jnp.ones(4))
    snap = obs.snapshot()
    snap["ops"]["AllReduce"]["emissions"] = 999
    snap["emissions"].clear()
    fresh = obs.snapshot()
    assert fresh["ops"]["AllReduce"]["emissions"] == 1
    assert len(fresh["emissions"]) == 1


def test_reset_clears_counters_and_ring():
    obs.enable()
    m4t.allreduce(jnp.ones(4))
    assert obs.snapshot()["totals"]["emissions"] == 1
    obs.reset()
    snap = obs.snapshot()
    assert snap["totals"]["emissions"] == 0
    assert snap["ops"] == {} and snap["emissions"] == []


def test_report_lists_ops_and_totals():
    obs.enable()
    m4t.allreduce(jnp.ones((4, 2), jnp.float32))
    m4t.allgather(jnp.ones(3, jnp.int8))
    text = obs.report()
    assert "AllReduce" in text and "AllGather" in text
    assert "2 emissions" in text


def test_reservoir_bounded_and_exact_aggregates():
    r = Reservoir(capacity=16)
    for i in range(1000):
        r.add(float(i))
    assert r.count == 1000
    assert r.minimum == 0.0 and r.maximum == 999.0
    assert len(r.samples) == 16  # bounded regardless of stream length
    s = r.summary()
    assert s["count"] == 1000 and s["mean"] == pytest.approx(499.5)
    assert s["p50"] is not None


def test_registry_independent_instances():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.record_emission(
        "X", nbytes=4, dtype="float32", axes=(), world=1, cid="aaaaaaaa"
    )
    assert a.snapshot()["totals"]["emissions"] == 1
    assert b.snapshot()["totals"]["emissions"] == 0


# ---------------------------------------------------------------------------
# JSONL events
# ---------------------------------------------------------------------------


def test_event_log_roundtrip_and_probe_schema(tmp_path):
    path = tmp_path / "events.jsonl"
    log = events.EventLog(str(path))
    written = log.append(events.event("probe", outcome="ok", attempt=3))
    # tpu_watch-shaped records (no "kind") share the same sink format
    log.append({"stage": "bench", "exit_code": 0, "captured": []})

    records = events.read(str(path))
    assert len(records) == 2
    first, second = records
    assert first == written
    assert first["kind"] == "probe" and first["outcome"] == "ok"
    # ts is stamped in the shared probe-log format
    for rec in records:
        time.strptime(rec["ts"], events.TS_FORMAT)
    # raw lines are one JSON object each (JSONL)
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    assert all(isinstance(json.loads(ln), dict) for ln in lines)


def test_emission_events_flow_to_sink(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    events.set_sink(str(path))
    obs.enable()
    m4t.allreduce(jnp.ones((4, 2), jnp.float32))
    m4t.allgather(jnp.ones(3, jnp.int8))

    records = events.read(str(path))
    assert [r["op"] for r in records] == ["AllReduce", "AllGather"]
    for rec in records:
        assert rec["kind"] == "emission"
        assert set(rec) >= {
            "kind", "cid", "op", "bytes", "dtype", "axes", "world", "ts",
            "annotation",
        }
        time.strptime(rec["ts"], events.TS_FORMAT)
    assert records[0]["bytes"] == 32 and records[1]["bytes"] == 3
    # the event stream and the registry ring agree record-for-record
    ring = obs.snapshot()["emissions"]
    assert [r["cid"] for r in records] == [r["cid"] for r in ring]


def test_no_sink_means_no_file(tmp_path):
    obs.enable()
    m4t.allreduce(jnp.ones(4))
    assert events.get_sink() is None  # fixture cleared it
    assert events.emit({"kind": "x"}) is None


def test_malformed_lines_skipped(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text('{"kind": "ok"}\n{"torn...\n')
    records = events.read(str(path))
    assert len(records) == 1 and records[0]["kind"] == "ok"


# ---------------------------------------------------------------------------
# disabled path is zero-overhead
# ---------------------------------------------------------------------------


def _trace_text(fn, *args):
    return str(jax.make_jaxpr(fn)(*args))


def test_disabled_no_records_and_no_callbacks():
    assert not obs.enabled()

    def program(x):
        y = m4t.allreduce(x + 1)
        return m4t.allgather(y)

    trace = _trace_text(program, jnp.ones(8))
    assert "callback" not in trace
    assert obs.snapshot()["totals"]["emissions"] == 0


def test_enabled_without_runtime_adds_no_callbacks():
    """Trace-time counters must not change the traced computation:
    telemetry on (runtime sampling off) produces an identical jaxpr
    modulo nothing — in particular, zero host callbacks. Fresh
    function objects per trace: jax caches tracing per fn object."""
    def make_program():
        def program(x):
            return m4t.allreduce(x * 2)

        return program

    baseline = _trace_text(make_program(), jnp.ones(8))
    obs.enable(runtime=False)
    with_telemetry = _trace_text(make_program(), jnp.ones(8))
    assert with_telemetry == baseline
    assert "callback" not in with_telemetry
    assert obs.snapshot()["ops"]["AllReduce"]["emissions"] == 1


def test_runtime_sampling_emits_callbacks_and_samples():
    obs.enable(runtime=True)

    def program(x):
        return m4t.allreduce(x + 1)

    trace = _trace_text(program, jnp.ones(8))
    assert "callback" in trace

    f = jax.jit(program)
    for _ in range(3):
        f(jnp.ones(8)).block_until_ready()
    jax.effects_barrier()
    lat = obs.snapshot()["ops"]["AllReduce"]["latency_s"]
    assert lat["count"] >= 1
    assert lat["min"] >= 0


# ---------------------------------------------------------------------------
# correlation id ties log line <-> metric record <-> annotation
# ---------------------------------------------------------------------------


def test_correlation_id_shared_across_layers(capsys):
    obs.enable()
    m4t.set_logging(True)
    try:
        m4t.allreduce(jnp.ones(4))
    finally:
        m4t.set_logging(False)

    out = capsys.readouterr().out
    emit_lines = [ln for ln in out.splitlines() if ln.startswith("emit | ")]
    assert len(emit_lines) == 1
    cid_from_log = emit_lines[0].split(" | ")[1]

    rec = obs.snapshot()["emissions"][-1]
    assert rec["cid"] == cid_from_log
    assert rec["annotation"] == f"m4t.allreduce.{cid_from_log}"
    assert obs.snapshot()["ops"]["AllReduce"]["last_cid"] == cid_from_log


def test_annotation_scope_lands_in_compiled_hlo(mesh):
    """The m4t.<op>.<cid> named scope must reach compiled-HLO op
    metadata — that is what makes XLA profiler traces attribute
    collective time to the mpi4jax-level op."""
    from mpi4jax_tpu.parallel import spmd

    obs.enable()
    compiled = (
        jax.jit(lambda x: spmd(lambda y: m4t.allreduce(y), mesh=mesh)(x))
        .lower(jnp.zeros((8, 3)))
        .compile()
    )
    hlo = compiled.as_text()
    cid = obs.snapshot()["ops"]["AllReduce"]["last_cid"]
    assert f"m4t.allreduce.{cid}" in hlo


def test_per_op_and_global_seq_counters():
    """ISSUE-2 satellite: record_emission carries monotonic sequence
    numbers — global (``seq``, cross-rank alignment key) and per-op
    (``op_seq``, exposed in snapshot()) — and reset() zeroes both."""
    obs.enable()
    m4t.allreduce(jnp.ones(4))
    m4t.allgather(jnp.ones(4))
    m4t.allreduce(jnp.ones(4))
    snap = obs.snapshot()
    assert snap["ops"]["AllReduce"]["seq"] == 2
    assert snap["ops"]["AllGather"]["seq"] == 1
    assert snap["totals"]["seq"] == 3
    assert [r["seq"] for r in snap["emissions"]] == [1, 2, 3]
    assert [r["op_seq"] for r in snap["emissions"]] == [1, 1, 2]
    obs.reset()
    m4t.allreduce(jnp.ones(4))
    snap = obs.snapshot()
    assert snap["ops"]["AllReduce"]["seq"] == 1
    assert snap["emissions"][0]["seq"] == 1


def test_rank_templated_sink(tmp_path, monkeypatch):
    """ISSUE-2 satellite: a {rank} placeholder in the sink path is
    resolved from M4T_RANK, giving each rank its own file."""
    monkeypatch.setenv("M4T_RANK", "7")
    sink = events.set_sink(str(tmp_path / "events-rank{rank}.jsonl"))
    assert sink.path.endswith("events-rank7.jsonl")
    obs.enable()
    m4t.allreduce(jnp.ones(4))
    (rec,) = events.read(str(tmp_path / "events-rank7.jsonl"))
    assert rec["rank"] == 7  # emit() stamps the rank into each record
    assert rec["op"] == "AllReduce" and rec["seq"] == 1


def test_current_rank_resolution(monkeypatch):
    monkeypatch.setenv("M4T_RANK", "3")
    assert events.current_rank() == 3
    monkeypatch.delenv("M4T_RANK")
    assert events.current_rank() == 0
    assert events.expand_rank_template("a/b-{rank}.jsonl", 5) == "a/b-5.jsonl"
    assert events.expand_rank_template("plain.jsonl") == "plain.jsonl"


def test_event_log_fsync_mode(tmp_path):
    """ISSUE-2 satellite: crash-safe flush — every append is on disk
    (line-buffered + fsync) the moment it returns."""
    path = str(tmp_path / "durable.jsonl")
    log = events.EventLog(path, fsync=True)
    log.append(events.event("emission", op="AllReduce", seq=1))
    # read through a separate handle WITHOUT closing the writer: the
    # line must already be durable
    (rec,) = events.read(path)
    assert rec["op"] == "AllReduce"
    log.append(events.event("emission", op="AllGather", seq=2))
    assert [r["op"] for r in events.read(path)] == ["AllReduce", "AllGather"]
    log.close()


def test_latency_samples_mirrored_as_events(tmp_path):
    """Runtime latency samples reach the event sink as ``latency``
    records (the doctor's straggler evidence), tagged with the
    emission's seq."""
    path = str(tmp_path / "ev.jsonl")
    events.set_sink(path)
    obs.enable(runtime=True)
    f = jax.jit(lambda x: m4t.allreduce(x + 1))
    for _ in range(2):
        f(jnp.ones(8)).block_until_ready()
    jax.effects_barrier()
    recs = events.read(path)
    lat = [r for r in recs if r["kind"] == "latency"]
    assert lat, recs
    emission_seq = [r for r in recs if r["kind"] == "emission"][0]["seq"]
    for r in lat:
        assert r["op"] == "AllReduce"
        assert r["seconds"] >= 0
        assert r["seq"] == emission_seq
        assert r["rank"] == 0


def test_heartbeat_records(tmp_path):
    path = str(tmp_path / "hb.jsonl")
    events.set_sink(path)
    rec = events.heartbeat("test", n=1)
    assert rec["kind"] == "heartbeat" and rec["source"] == "test"
    assert isinstance(rec["t"], float) and "rank" in rec
    assert events.read(path)[0]["kind"] == "heartbeat"
    # without a sink: no-op, and start_heartbeat declines to spawn
    events.set_sink(None)
    assert events.heartbeat("test") is None
    stop = events.start_heartbeat(0.01)
    stop()


def test_annotation_plain_when_disabled():
    """With telemetry off the scope stays the stable aggregate name
    (no cid suffix), so profiles group by op."""
    hlo = (
        jax.jit(lambda x: m4t.allreduce(x))
        .lower(jnp.zeros(4))
        .compile()
        .as_text()
    )
    assert "m4t.allreduce." not in hlo  # no per-emission suffix


# ---------------------------------------------------------------------------
# Reservoir properties (algorithm R) — the attribution layer
# (observability/perf.py) trusts these summaries, so they are pinned
# ---------------------------------------------------------------------------


def test_reservoir_exact_aggregates_on_long_stream():
    """count/sum/min/max are exact over the whole stream no matter how
    small the reservoir."""
    import random as _random

    _random.seed(1234)
    r = Reservoir(16)
    values = [_random.uniform(0.001, 5.0) for _ in range(5000)]
    for v in values:
        r.add(v)
    assert r.count == 5000
    assert len(r.samples) == 16  # capacity never exceeded
    assert r.total == pytest.approx(sum(values))
    assert r.minimum == pytest.approx(min(values))
    assert r.maximum == pytest.approx(max(values))
    s = r.summary()
    assert s["count"] == 5000
    assert s["mean"] == pytest.approx(sum(values) / 5000)
    # every retained sample is a real member of the stream
    assert all(v in values for v in r.samples)


def test_reservoir_empty_and_singleton_summaries():
    r = Reservoir(8)
    s = r.summary()
    assert s == {"count": 0, "mean": None, "min": None, "max": None,
                 "p50": None, "p90": None, "p99": None}
    assert r.quantile(0.5) is None
    r.add(0.25)
    s = r.summary()
    assert s["count"] == 1
    assert s["mean"] == s["min"] == s["max"] == 0.25
    assert s["p50"] == s["p90"] == s["p99"] == 0.25


def test_reservoir_quantile_monotonicity_and_bounds():
    """p50 <= p90 <= p99, and every quantile lies within [min, max] —
    for streams shorter and longer than the capacity."""
    import random as _random

    _random.seed(99)
    for n in (3, 7, 64, 256, 2000):
        r = Reservoir(64)
        for _ in range(n):
            r.add(_random.expovariate(10.0))
        s = r.summary()
        assert s["p50"] <= s["p90"] <= s["p99"]
        assert r.minimum <= s["p50"] and s["p99"] <= r.maximum
        # quantiles over the full grid are monotone too
        qs = [r.quantile(q / 10) for q in range(11)]
        assert qs == sorted(qs)
        assert qs[0] == min(r.samples) and qs[-1] == max(r.samples)


def test_reservoir_uniform_sample_is_plausible():
    """Distribution sanity for the algorithm-R replacement policy: the
    retained sample of a long uniform stream should cover the range,
    not cluster at either end (a biased j-index would)."""
    import random as _random

    _random.seed(7)
    r = Reservoir(128)
    for i in range(10000):
        r.add(float(i))
    mean_sample = sum(r.samples) / len(r.samples)
    assert 3000 < mean_sample < 7000
    assert r.quantile(0.0) >= 0 and r.quantile(1.0) <= 9999
