"""Resilience subsystem (``mpi4jax_tpu/resilience/``): fault
injection, checkpoint management, and the self-healing supervisor.

Covers the ISSUE-5 acceptance surface:

- fault-plan parsing: every malformed spec class gets a clear
  ``FaultPlanError`` (bad JSON, unknown op, out-of-range rank, bad
  action/nth/ms/p), and matching/arming semantics (rank scoping, Nth
  emission, fingerprint rules, attempt scoping, seeded probability);
- injection through the real emission path (``ops/_core.py``): armed
  delay rules fire at the Nth ``m4t.allreduce``, are logged as
  ``fault`` JSONL events, and cost nothing when unarmed;
- CheckpointManager: atomic commit protocol (manifest-last), retention
  of the newest K, ``latest_valid()``/``at_step()`` skipping torn,
  truncated, or world/fingerprint-mismatched checkpoints — both on the
  device-free JSON storage layer and on the real orbax one;
- supervisor: verdict classification (transient vs deterministic),
  bounded exponential backoff, audit-log records, fail-fast on
  MISMATCH, interrupt passthrough;
- the launcher: ``--retries 0`` backward compat (single attempt, flat
  artifact layout, same exit codes, no supervisor.jsonl), supervised
  retry layout (per-attempt dirs + audit log), ``--fault-plan``
  validation at spawn time;
- chaos e2e (slow, ``-m chaos``): a 2-rank run with an injected rank-1
  crash at step N is restarted by the supervisor, resumes from the
  latest valid checkpoint, and reproduces the fault-free run's final
  parameters bit-for-bit; a MISMATCH-class failure is *not* retried.
"""

import json
import os
import re
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu.observability import events
from mpi4jax_tpu.resilience import (
    PREEMPT_EXIT,
    CheckpointManager,
    FaultPlan,
    FaultPlanError,
    InjectedFault,
    PreemptGuard,
    RetryPolicy,
    Supervisor,
    classify,
    faults,
    resume_step,
)
from mpi4jax_tpu.resilience.ckpt import pytree_fingerprint

pytestmark = pytest.mark.resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_state():
    yield
    faults.disarm()
    events.set_sink(None)


# ---------------------------------------------------------------------
# fault-plan parsing
# ---------------------------------------------------------------------


def test_plan_parses_full_form():
    plan = FaultPlan.parse(json.dumps({
        "seed": 3,
        "faults": [
            {"rank": 1, "op": "AllReduce", "nth": 6, "action": "crash"},
            {"rank": [0, 2], "op": "*", "action": "delay", "ms": 10},
            {"rank": "*", "fingerprint": "Barrier[scalar:uint32]@<none>",
             "action": "hang"},
            {"rank": 0, "op": "AllGather", "action": "slowdown",
             "ms": 5, "nth": 2, "p": 0.5, "attempt": 1},
        ],
    }))
    assert plan.seed == 3
    assert [r.action for r in plan.rules] == [
        "crash", "delay", "hang", "slowdown"]
    assert plan.rules[0].mode == "exception"
    assert plan.rules[3].attempt == 1


def test_plan_parses_bare_list_shorthand():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "Barrier", "action": "hang"}]'
    )
    assert len(plan.rules) == 1 and plan.seed == 0


def test_plan_load_from_file(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text('[{"rank": 0, "op": "Barrier", "action": "hang"}]')
    assert len(FaultPlan.load(str(p)).rules) == 1
    # and inline JSON when no such file exists
    assert len(FaultPlan.load(
        '[{"rank": 0, "op": "Barrier", "action": "hang"}]'
    ).rules) == 1


@pytest.mark.parametrize("spec,needle", [
    ("{oops", "not valid JSON"),
    ("42", "must be a JSON object"),
    ('{"faults": []}', "non-empty"),
    ('{"faults": [{}], "extra": 1}', "unknown top-level"),
    ('[{"rank": 0, "op": "FooBar", "action": "hang"}]', "unknown op"),
    ('[{"rank": 0, "op": "Barrier", "action": "fizzle"}]', "action"),
    ('[{"rank": -2, "op": "Barrier", "action": "hang"}]', "negative"),
    ('[{"rank": "x", "op": "Barrier", "action": "hang"}]', "rank"),
    ('[{"rank": 0, "action": "hang"}]', "'op' or 'fingerprint'"),
    ('[{"rank": 0, "op": "Barrier", "fingerprint": "f", '
     '"action": "hang"}]', "mutually exclusive"),
    ('[{"rank": 0, "op": "Barrier", "action": "delay"}]', "ms"),
    ('[{"rank": 0, "op": "Barrier", "action": "hang", "nth": 0}]', "nth"),
    ('[{"rank": 0, "op": "Barrier", "action": "hang", "p": 2}]', "p"),
    ('[{"rank": 0, "op": "Barrier", "action": "hang", "typo": 1}]',
     "unknown field"),
    ('[{"rank": 0, "op": "Barrier", "action": "crash", '
     '"mode": "panic"}]', "mode"),
], ids=lambda v: (v[:24] if isinstance(v, str) else v))
def test_plan_parse_errors_are_clear(spec, needle):
    with pytest.raises(FaultPlanError) as exc:
        FaultPlan.parse(spec)
    assert needle in str(exc.value), (
        f"error {exc.value} should mention {needle!r}"
    )


def test_plan_world_validation():
    plan = FaultPlan.parse(
        '[{"rank": 3, "op": "Barrier", "action": "hang"}]'
    )
    plan.validate_world(4)
    with pytest.raises(FaultPlanError, match="out of range"):
        plan.validate_world(2)
    # wildcard ranks validate against any world
    FaultPlan.parse(
        '[{"rank": "*", "op": "Barrier", "action": "hang"}]'
    ).validate_world(1)


# ---------------------------------------------------------------------
# matching + injection (direct hook calls)
# ---------------------------------------------------------------------


def _emit_n(op, n, **kw):
    for _ in range(n):
        faults.on_emission(op, cid="t", nbytes=16, dtype="float32",
                           shape=(4,), axes=[], world=2, **kw)


def test_rank_scoping_and_nth():
    plan = FaultPlan.parse(
        '[{"rank": 1, "op": "AllReduce", "nth": 2, "action": "delay",'
        ' "ms": 1}]'
    )
    faults.arm(plan, rank=0)
    _emit_n("AllReduce", 5)
    assert plan.rules[0].fired == 0  # wrong rank: never fires
    faults.arm(plan, rank=1)
    _emit_n("AllReduce", 5)
    assert plan.rules[0].matches == 5
    assert plan.rules[0].fired == 1  # nth=2 exactly once


def test_slowdown_fires_from_nth_on():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "nth": 3, "action": "slowdown",'
        ' "ms": 1}]'
    )
    faults.arm(plan, rank=0)
    _emit_n("AllReduce", 6)
    assert plan.rules[0].fired == 4  # emissions 3,4,5,6


def test_fingerprint_rule_matches_exactly():
    fp = "AllReduce[4:float32]@<none>"
    plan = FaultPlan.parse(json.dumps([
        {"rank": 0, "fingerprint": fp, "action": "delay", "ms": 1},
    ]))
    faults.arm(plan, rank=0)
    # different shape -> different fingerprint -> no match
    faults.on_emission("AllReduce", cid="t", nbytes=32, dtype="float32",
                       shape=(8,), axes=[], world=2)
    assert plan.rules[0].matches == 0
    _emit_n("AllReduce", 1)
    assert plan.rules[0].fired == 1


def test_crash_raises_injected_fault():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "Barrier", "action": "crash"}]'
    )
    faults.arm(plan, rank=0)
    with pytest.raises(InjectedFault, match="injected crash at Barrier"):
        _emit_n("Barrier", 1)


def test_attempt_scoped_rule():
    spec = ('[{"rank": 0, "op": "AllReduce", "action": "delay", '
            '"ms": 1, "attempt": 1}]')
    plan = FaultPlan.parse(spec)
    faults.arm(plan, rank=0, attempt=0)
    _emit_n("AllReduce", 3)
    assert plan.rules[0].fired == 0  # rule wants attempt 1
    faults.arm(plan, rank=0, attempt=1)
    _emit_n("AllReduce", 3)
    assert plan.rules[0].fired == 1


def test_probability_zero_never_fires_and_is_seeded():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "action": "delay", "ms": 1,'
        ' "p": 0.0}]'
    )
    faults.arm(plan, rank=0)
    _emit_n("AllReduce", 10)
    assert plan.rules[0].fired == 0
    # p=1 always fires; and a fixed seed gives reproducible decisions
    # for fractional p (same plan, same rank -> same outcome)
    spec = ('{"seed": 11, "faults": [{"rank": 0, "op": "AllReduce",'
            ' "action": "slowdown", "ms": 1, "p": 0.5}]}')
    outcomes = []
    for _ in range(2):
        plan2 = FaultPlan.parse(spec)
        faults.arm(plan2, rank=0)
        _emit_n("AllReduce", 8)
        outcomes.append(plan2.rules[0].fired)
    assert outcomes[0] == outcomes[1]


def test_preempt_rule_parses_with_crash_scoping():
    plan = FaultPlan.parse(json.dumps([
        {"rank": [2, 3], "op": "AllReduce", "nth": 6,
         "action": "preempt", "attempt": 0},
        {"rank": "*", "op": "Barrier", "action": "preempt", "p": 0.5},
    ]))
    assert [r.action for r in plan.rules] == ["preempt", "preempt"]
    assert plan.rules[0].attempt == 0 and plan.rules[0].nth == 6
    plan.validate_world(4)
    with pytest.raises(FaultPlanError, match="out of range"):
        plan.validate_world(3)


def test_preempt_fires_sigterm_once(elastic_sigterm_flag):
    """The preempt action delivers SIGTERM to this process at exactly
    the Nth matching emission — and is survivable (the handler runs,
    execution continues)."""
    flag = elastic_sigterm_flag
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "nth": 2, "action": "preempt"}]'
    )
    faults.arm(plan, rank=0)
    _emit_n("AllReduce", 1)
    assert flag() == 0
    _emit_n("AllReduce", 3)  # nth=2 fires once; later matches don't
    assert flag() == 1
    assert plan.rules[0].fired == 1


@pytest.fixture
def elastic_sigterm_flag():
    """Temporarily swap in a counting SIGTERM handler (and restore the
    previous one) so preempt-action tests observe the signal instead
    of dying on it."""
    import signal as _signal

    hits = []
    prev = _signal.signal(_signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        yield lambda: len(hits)
    finally:
        _signal.signal(_signal.SIGTERM, prev)


def test_preempt_guard_flag_and_exit(elastic_sigterm_flag):
    import signal as _signal

    guard = PreemptGuard()  # replaces the fixture's handler; restored after
    assert not guard.preempted
    guard.exit_if_preempted()  # no-op while unflagged
    os.kill(os.getpid(), _signal.SIGTERM)
    assert guard.preempted
    saved = []
    with pytest.raises(SystemExit) as exc:
        guard.exit_if_preempted(save_fn=lambda: saved.append(1))
    assert exc.value.code == PREEMPT_EXIT == 143
    assert saved == [1]


def test_preempt_guard_second_signal_mid_checkpoint_escalates():
    """ISSUE-10 satellite: a second SIGTERM arriving while the guard
    is already inside the grace checkpoint escalates to an immediate
    PREEMPT_EXIT — no re-entrant checkpoint (the save this thread is
    mid-write in must not be re-entered from the handler)."""
    import signal as _signal

    guard = PreemptGuard(install=False)
    exits = []

    class _Escaped(BaseException):
        pass

    def fake_exit():
        exits.append(guard.exit_code)
        raise _Escaped()  # stand-in for os._exit: never returns

    guard._exit_now = fake_exit
    guard._on_signal(_signal.SIGTERM, None)  # first notice: flag only
    assert guard.preempted and exits == []

    saves = []

    def save_fn():
        saves.append("started")
        guard._on_signal(_signal.SIGTERM, None)  # notice mid-save
        saves.append("finished")  # unreachable: escalation left first

    with pytest.raises(_Escaped):
        guard.exit_if_preempted(save_fn=save_fn)
    assert exits == [PREEMPT_EXIT]
    assert saves == ["started"]  # the checkpoint was NOT re-entered
    assert guard._checkpointing is False  # window closed on the way out


def test_preempt_guard_second_signal_outside_checkpoint_waits():
    """Two notices *before* the step boundary keep waiting: the loop
    still gets to finish its step and take the grace checkpoint."""
    import signal as _signal

    guard = PreemptGuard(install=False)
    exits = []
    guard._exit_now = lambda: exits.append(True)
    guard._on_signal(_signal.SIGTERM, None)
    guard._on_signal(_signal.SIGTERM, None)
    assert guard.preempted and exits == []
    saved = []
    with pytest.raises(SystemExit) as exc:
        guard.exit_if_preempted(save_fn=lambda: saved.append(1))
    assert exc.value.code == PREEMPT_EXIT and saved == [1]


def test_preempt_guard_double_sigterm_real_signal(elastic_sigterm_flag):
    """Same escalation through real signal delivery: the second
    os.kill lands while save_fn runs, and the handler exits on the
    spot instead of letting the checkpoint finish."""
    import signal as _signal

    guard = PreemptGuard()  # fixture restores the handler afterwards

    class _Escaped(BaseException):
        pass

    def fake_exit():
        raise _Escaped()

    guard._exit_now = fake_exit
    os.kill(os.getpid(), _signal.SIGTERM)
    assert guard.preempted

    def save_fn():
        os.kill(os.getpid(), _signal.SIGTERM)
        time.sleep(0.05)  # the handler runs before this returns
        raise AssertionError("checkpoint survived the second notice")

    with pytest.raises(_Escaped):
        guard.exit_if_preempted(save_fn=save_fn)


def test_delay_actually_sleeps():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "action": "delay", "ms": 120}]'
    )
    faults.arm(plan, rank=0)
    t0 = time.perf_counter()
    _emit_n("AllReduce", 1)
    assert time.perf_counter() - t0 >= 0.1


def test_injection_logs_fault_event(tmp_path):
    sink_path = str(tmp_path / "events.jsonl")
    events.set_sink(sink_path, fsync=False)
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "nth": 2, "action": "delay",'
        ' "ms": 1}]'
    )
    faults.arm(plan, rank=0)
    _emit_n("AllReduce", 3)
    events.set_sink(None)
    recs = [r for r in events.read(sink_path) if r["kind"] == "fault"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["action"] == "delay" and rec["op"] == "AllReduce"
    assert rec["nth"] == 2 and rec["match"] == 2 and rec["rule"] == 0
    assert "AllReduce[4:float32]" in rec["fingerprint"]


# ---------------------------------------------------------------------
# injection through the real emission path (ops/_core.py)
# ---------------------------------------------------------------------


def test_armed_plan_fires_on_real_allreduce():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "nth": 2, "action": "delay",'
        ' "ms": 1}]'
    )
    faults.arm(plan, rank=0)
    m4t.allreduce(jnp.ones(3))
    assert plan.rules[0].matches == 1 and plan.rules[0].fired == 0
    m4t.allreduce(jnp.ones(3))
    assert plan.rules[0].fired == 1


def test_crash_through_real_emission_path():
    plan = FaultPlan.parse(
        '[{"rank": 0, "op": "AllReduce", "action": "crash"}]'
    )
    faults.arm(plan, rank=0)
    with pytest.raises(InjectedFault):
        m4t.allreduce(jnp.ones(3))
    faults.disarm()
    # disarmed: the same call is clean again
    np.testing.assert_array_equal(
        np.asarray(m4t.allreduce(jnp.ones(3))), np.ones(3)
    )


def test_unarmed_hook_is_inert():
    assert faults.active_plan is None
    m4t.allreduce(jnp.ones(3))  # no plan, no env: nothing to observe


# ---------------------------------------------------------------------
# CheckpointManager — device-free JSON storage layer
# ---------------------------------------------------------------------


def _json_save(path, state):
    with open(path, "w") as f:
        json.dump(state, f)


def _json_restore(path, template):
    with open(path) as f:
        return json.load(f)


def _json_mgr(root, **kw):
    kw.setdefault("save_fn", _json_save)
    kw.setdefault("restore_fn", _json_restore)
    return CheckpointManager(str(root), **kw)


def test_manager_save_restore_retention(tmp_path):
    mgr = _json_mgr(tmp_path / "ckpt", keep=2, world=2)
    for step in (1, 3, 7, 9):
        mgr.save(step, {"w": step}, fingerprint="fp")
    assert mgr.steps() == [7, 9]
    info = mgr.latest_valid(fingerprint="fp", world=2)
    assert info.step == 9
    assert info.manifest["world"] == 2
    assert mgr.restore(info, None) == {"w": 9}
    at7 = mgr.at_step(7, fingerprint="fp")
    assert at7 is not None and mgr.restore(at7, None) == {"w": 7}
    assert mgr.at_step(3) is None  # pruned


def test_manager_skips_torn_checkpoints(tmp_path):
    mgr = _json_mgr(tmp_path / "ckpt", keep=5)
    for step in (1, 2, 3):
        mgr.save(step, {"w": step}, fingerprint="fp")
    # step 3: manifest deleted (killed between data write and commit
    # cannot happen — rename is atomic — but operators truncate dirs)
    os.unlink(os.path.join(mgr.root, "step_00000003", "manifest.json"))
    # step 2: data removed, manifest intact
    os.unlink(os.path.join(mgr.root, "step_00000002", "data"))
    info = mgr.latest_valid(fingerprint="fp")
    assert info is not None and info.step == 1
    # corrupt manifest JSON is also skipped, not fatal
    with open(os.path.join(mgr.root, "step_00000001", "manifest.json"),
              "w") as f:
        f.write("{torn")
    assert mgr.latest_valid(fingerprint="fp") is None


def test_manager_world_and_fingerprint_mismatch_skipped(tmp_path):
    mgr = _json_mgr(tmp_path / "ckpt", keep=5, world=2)
    mgr.save(5, {"w": 5}, fingerprint="fpA")
    assert mgr.latest_valid(fingerprint="fpB") is None
    assert mgr.latest_valid(fingerprint="fpA", world=4) is None
    assert mgr.latest_valid(fingerprint="fpA", world=2).step == 5
    # unspecified fingerprint/world: manifest is not interrogated
    assert mgr.latest_valid().step == 5


def test_manager_step_tag_must_match_dirname(tmp_path):
    mgr = _json_mgr(tmp_path / "ckpt", keep=5)
    mgr.save(4, {"w": 4})
    os.rename(
        os.path.join(mgr.root, "step_00000004"),
        os.path.join(mgr.root, "step_00000009"),
    )
    # a renamed/copied dir whose manifest disagrees with its name is
    # not trusted at either address
    assert mgr.latest_valid() is None


def test_manager_sweeps_tmp_litter(tmp_path):
    mgr = _json_mgr(tmp_path / "ckpt", keep=5)
    litter = os.path.join(mgr.root, ".tmp-step_00000002.999")
    os.makedirs(litter)
    mgr.save(1, {"w": 1})
    assert not os.path.exists(litter)
    assert mgr.steps() == [1]


def test_latest_valid_tolerates_step_dir_vanishing_mid_scan(tmp_path):
    """ISSUE-10 satellite: keep-K retention in a concurrent writer
    (real under serving — the drain path reads while a resident job
    checkpoints) can delete a step dir between this reader's listing
    and its manifest read. The scan must fall through to an older
    committed step, never raise."""
    import shutil as _shutil

    mgr = _json_mgr(tmp_path / "ckpt", keep=5, world=2)
    for step in (1, 2, 3):
        mgr.save(step, {"w": step}, fingerprint="fp")

    orig_steps = mgr.steps

    def racing_steps():
        # the concurrent writer's prune lands right after our listing
        listed = orig_steps()
        _shutil.rmtree(os.path.join(mgr.root, "step_00000003"),
                       ignore_errors=True)
        return listed

    mgr.steps = racing_steps
    info = mgr.latest_valid(fingerprint="fp", world=2)
    assert info is not None and info.step == 2
    # same tolerance between the data existence check and the data
    # listing (the narrowest window): a listdir that hits a vanished
    # dir reads as "invalid", not a crash
    mgr.steps = orig_steps
    real_listdir = os.listdir
    data2 = os.path.join(mgr.root, "step_00000002", "data")

    def racing_listdir(path="."):
        if os.fspath(path) == data2:
            raise FileNotFoundError(2, "vanished mid-scan", path)
        return real_listdir(path)

    # make step 2's data a directory so the listdir branch runs
    os.unlink(data2)
    os.makedirs(data2)
    with open(os.path.join(data2, "payload"), "w") as f:
        f.write("{}")
    os.listdir = racing_listdir
    try:
        info = mgr.latest_valid(fingerprint="fp", world=2)
    finally:
        os.listdir = real_listdir
    assert info is not None and info.step == 1


def test_manager_atomic_layout(tmp_path):
    """The commit protocol's observable invariant: a committed step
    dir holds data + manifest, and the manifest certifies the step."""
    mgr = _json_mgr(tmp_path / "ckpt", keep=5, world=1)
    info = mgr.save(12, {"w": 1}, fingerprint="fp")
    names = sorted(os.listdir(info.path))
    assert names == ["data", "manifest.json"]
    manifest = json.load(open(os.path.join(info.path, "manifest.json")))
    assert manifest["step"] == 12
    assert manifest["schema"] == "m4t-ckpt/1"
    assert manifest["fingerprint"] == "fp"
    assert manifest["world"] == 1


# ---------------------------------------------------------------------
# CheckpointManager — real (orbax) storage layer
# ---------------------------------------------------------------------


@pytest.fixture
def orbax():
    return pytest.importorskip("orbax.checkpoint")


def test_manager_orbax_roundtrip_and_fingerprint(tmp_path, orbax):
    state = {"w": jnp.arange(6.0).reshape(2, 3),
             "b": jnp.ones(3, jnp.float32)}
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2, world=1)
    info = mgr.save(3, state)
    assert info.manifest["fingerprint"] == pytree_fingerprint(state)
    step, restored = mgr.restore_latest(
        {"w": jnp.zeros((2, 3)), "b": jnp.zeros(3, jnp.float32)}
    )
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    # a template with a different structure refuses to resume
    assert mgr.restore_latest({"other": jnp.zeros(4)}) is None


def test_manager_orbax_truncated_checkpoint_skipped(tmp_path, orbax):
    state = {"w": jnp.arange(4.0)}
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5)
    mgr.save(1, state)
    mgr.save(2, state)
    # truncate the newest: drop its manifest (simulating a dir copied
    # mid-write); resume must fall back to step 1, not die
    os.unlink(os.path.join(mgr.root, "step_00000002", "manifest.json"))
    step, restored = mgr.restore_latest({"w": jnp.zeros(4)})
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(4.0))


# ---------------------------------------------------------------------
# classification + retry policy + supervisor loop
# ---------------------------------------------------------------------


def test_classify_matrix():
    assert classify(None, 0)["klass"] == "clean"
    assert classify({"findings": []}, 0)["klass"] == "clean"
    assert classify(None, 1) == {
        "klass": "transient", "reason": "crash_no_telemetry", "kinds": [],
    }
    assert classify({"findings": []}, 1)["reason"] == (
        "crash_without_mismatch")
    hang = {"findings": [{"kind": "hang", "rank": 1, "verdict": "hung"}]}
    assert classify(hang, 124) == {
        "klass": "transient", "reason": "hang", "kinds": ["hang"]}
    assert classify(hang, 1)["reason"] == "transient_findings"
    missing = {"findings": [{"kind": "missing_rank", "rank": 1}]}
    assert classify(missing, 1)["klass"] == "transient"
    strag = {"findings": [{"kind": "straggler", "rank": 0, "op": "X"}]}
    assert classify(strag, 1)["klass"] == "transient"
    mm = {"findings": [{"kind": "mismatch", "seq": 2, "groups": []}]}
    assert classify(mm, 1)["klass"] == "deterministic"
    # mismatch beats a hang recorded beside it (causality order)
    assert classify(
        {"findings": mm["findings"] + hang["findings"]}, 124
    )["klass"] == "deterministic"
    # a static-site join upgrades the reason (same class)
    mm_static = {"findings": [{
        "kind": "mismatch", "seq": 2,
        "groups": [{"fingerprint": "f", "ranks": [0], "static_sites": [
            {"source": "a.py:3"}]}],
    }]}
    assert classify(mm_static, 1)["reason"] == "mismatch_static_attributed"


def test_classify_preempted():
    # a rank declared preemption on the way out: transient, named
    assert classify(None, PREEMPT_EXIT) == {
        "klass": "transient", "reason": "preempted", "kinds": [],
    }
    # survivors' logs show hang/missing shapes — still "preempted"
    hangish = {"findings": [
        {"kind": "hang", "rank": 0, "verdict": "hung"},
        {"kind": "missing_rank", "rank": 3, "world": 4},
    ]}
    v = classify(hangish, PREEMPT_EXIT)
    assert v["klass"] == "transient" and v["reason"] == "preempted"
    assert v["kinds"] == ["hang", "missing_rank"]
    # but a MISMATCH still wins: a diverged program that also got
    # preempted will diverge again
    mm = {"findings": [{"kind": "mismatch", "seq": 2, "groups": []}]}
    assert classify(mm, PREEMPT_EXIT)["klass"] == "deterministic"


def test_supervisor_extra_fn_audits_world_transitions(tmp_path):
    """The elastic launcher records world-size transitions through
    extra_fn; the audit record must carry them (the doctor's
    supervisor timeline narrates exactly these fields)."""
    audit = str(tmp_path / "supervisor.jsonl")
    worlds = {0: 4, 1: 2}
    state = {"attempt": 0}

    def run_fn(attempt, resume):
        state["attempt"] = attempt
        return PREEMPT_EXIT if attempt == 0 else 0

    def extra_fn(attempt):
        rec = {"world": worlds[attempt]}
        if attempt == 0:
            rec.update(
                preempted_ranks=[2, 3], next_world=2,
                resharded_from_step=5, resharded_from_world=4,
            )
        return rec

    sup = Supervisor(
        run_fn,
        policy=RetryPolicy(retries=2, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: None,
        resume_fn=lambda: 5,
        extra_fn=extra_fn,
        audit_path=audit,
        sleep_fn=lambda s: None,
    )
    assert sup.run() == 0
    recs = events.read(audit)
    assert [r["action"] for r in recs] == ["retry", "done"]
    first = recs[0]
    assert first["world"] == 4 and first["next_world"] == 2
    assert first["preempted_ranks"] == [2, 3]
    assert first["resharded_from_step"] == 5
    assert first["reason"] == "preempted"
    assert recs[1]["world"] == 2 and "next_world" not in recs[1]
    # a broken extra_fn must not break the supervisor
    sup2 = Supervisor(
        lambda a, r: 0,
        policy=RetryPolicy(retries=0),
        extra_fn=lambda a: 1 / 0,
        sleep_fn=lambda s: None,
    )
    assert sup2.run() == 0


def test_doctor_narrates_supervisor_timeline(tmp_path):
    from mpi4jax_tpu.observability import doctor

    rundir = tmp_path / "run"
    attempt = rundir / "attempt00"
    attempt.mkdir(parents=True)
    log = events.EventLog(str(rundir / "supervisor.jsonl"))
    log.append(events.event(
        "supervisor", attempt=0, exit_code=143, klass="transient",
        reason="preempted", action="retry", world=4,
        preempted_ranks=[2, 3], next_world=2, resharded_from_step=5,
        resharded_from_world=4, resume_step=5,
    ))
    log.append(events.event(
        "supervisor", attempt=1, exit_code=0, klass="clean",
        reason="exit_zero", action="done", world=2,
    ))
    # found from the attempt dir (one level below the audit log)
    recs = doctor.load_supervisor_audit([str(attempt)])
    assert len(recs) == 2
    text = doctor.format_supervisor_timeline(recs)
    assert "attempt 0: world 4" in text
    assert "rank(s) 2,3 preempted" in text
    assert "ELASTIC: world 4 -> 2" in text
    assert "step 5 (world 4) resharded for 2 rank(s)" in text
    assert "attempt 1: world 2" in text and "clean" in text


def test_retry_policy_backoff():
    p = RetryPolicy(retries=4, backoff_s=0.5, jitter=0.0)
    assert [p.delay(a) for a in range(5)] == [0.0, 0.5, 1.0, 2.0, 4.0]
    assert RetryPolicy(backoff_s=1.0, max_backoff_s=3.0,
                       jitter=0.0).delay(10) == 3.0
    # jitter stays within +-25% of the base
    jp = RetryPolicy(backoff_s=1.0, jitter=0.25)
    for attempt in range(1, 6):
        base = min(1.0 * 2 ** (attempt - 1), 60.0)
        d = jp.delay(attempt)
        assert 0.74 * base <= d <= 1.26 * base


def test_supervisor_transient_retries_then_success(tmp_path):
    audit = str(tmp_path / "supervisor.jsonl")
    calls = []
    sup = Supervisor(
        lambda attempt, resume: calls.append((attempt, resume)) or (
            0 if attempt == 2 else 1),
        policy=RetryPolicy(retries=4, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: {"findings": []},
        resume_fn=lambda: 5,
        audit_path=audit,
        sleep_fn=lambda s: None,
    )
    assert sup.run() == 0
    assert calls == [(0, None), (1, 5), (2, 5)]
    recs = events.read(audit)
    assert [r["action"] for r in recs] == ["retry", "retry", "done"]
    assert all(r["kind"] == "supervisor" for r in recs)
    assert recs[0]["klass"] == "transient"


def test_supervisor_fails_fast_on_mismatch():
    calls = []
    sup = Supervisor(
        lambda attempt, resume: calls.append(attempt) or 1,
        policy=RetryPolicy(retries=9, backoff_s=0.0),
        diagnose_fn=lambda attempt: {
            "findings": [{"kind": "mismatch", "seq": 1, "groups": []}]},
        sleep_fn=lambda s: None,
    )
    assert sup.run() == 1
    assert calls == [0]
    assert sup.attempts[-1]["klass"] == "deterministic"
    assert sup.attempts[-1]["action"] == "give_up"


def test_supervisor_bounded_and_interrupt():
    calls = []
    sup = Supervisor(
        lambda attempt, resume: calls.append(attempt) or 3,
        policy=RetryPolicy(retries=2, backoff_s=0.0, jitter=0.0),
        diagnose_fn=lambda attempt: None,
        sleep_fn=lambda s: None,
    )
    assert sup.run() == 3
    assert calls == [0, 1, 2]
    # SIGINT (130) is the operator: never retried
    calls2 = []
    sup2 = Supervisor(
        lambda attempt, resume: calls2.append(attempt) or 130,
        policy=RetryPolicy(retries=5, backoff_s=0.0),
        sleep_fn=lambda s: None,
    )
    assert sup2.run() == 130
    assert calls2 == [0]
    assert sup2.attempts[-1]["klass"] == "interrupted"


def test_resume_step_reads_env(monkeypatch):
    monkeypatch.delenv("M4T_RESUME_STEP", raising=False)
    assert resume_step() is None
    monkeypatch.setenv("M4T_RESUME_STEP", "17")
    assert resume_step() == 17
    monkeypatch.setenv("M4T_RESUME_STEP", "bogus")
    assert resume_step() is None


# ---------------------------------------------------------------------
# CLI selftest smoke (tier-1 hook, mirrors perf --selftest)
# ---------------------------------------------------------------------


def test_cli_selftest():
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.resilience", "--selftest"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert res.returncode == 0, res.stderr
    assert "resilience selftest ok" in res.stdout


# ---------------------------------------------------------------------
# launcher integration (real worlds; native toolchain required)
# ---------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


def _launch(tmp_path, n, script, *launch_args, timeout=240,
            script_args=()):
    path = str(tmp_path / "case.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n),
         *launch_args, path, *script_args],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


@needs_native
def test_launch_retries_zero_is_single_attempt_backcompat(tmp_path):
    """``--retries 0`` (the default) must preserve the pre-supervisor
    contract: one attempt, flat --events-dir layout, the failing
    rank's exit code, and no supervisor audit artifacts."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 1,
        """
        import sys
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        m4t.allreduce(jnp.ones(3))
        sys.exit(3)
        """,
        "--events-dir", rundir,
    )
    assert res.returncode == 3, (res.returncode, res.stderr)
    produced = sorted(os.listdir(rundir))
    assert "events-rank0.jsonl" in produced  # flat, not attempt00/
    assert "supervisor.jsonl" not in produced
    assert not any(p.startswith("attempt") for p in produced)
    # failure still gets the inline doctor diagnosis (old behavior)
    assert "post-mortem diagnosis" in res.stderr


@needs_native
def test_launch_supervised_layout_and_audit(tmp_path):
    """--retries K: per-attempt artifact dirs, a supervisor.jsonl
    audit trail, and the transient crash is retried exactly K times."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 1,
        """
        import sys
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        m4t.allreduce(jnp.ones(3))
        sys.exit(2)
        """,
        "--events-dir", rundir, "--retries", "2", "--backoff", "0.05",
    )
    assert res.returncode == 2, (res.returncode, res.stderr)
    produced = sorted(os.listdir(rundir))
    assert {"attempt00", "attempt01", "attempt02"} <= set(produced)
    recs = events.read(os.path.join(rundir, "supervisor.jsonl"))
    assert [r["attempt"] for r in recs] == [0, 1, 2]
    assert [r["action"] for r in recs] == ["retry", "retry", "give_up"]
    assert all(r["klass"] == "transient" for r in recs)


@needs_native
def test_launch_rejects_bad_fault_plan(tmp_path):
    res = _launch(
        tmp_path, 1, "print('unreachable')",
        "--fault-plan", '[{"rank": 5, "op": "Barrier", "action": "hang"}]',
    )
    assert res.returncode == 2
    assert "out of range" in res.stderr
    res2 = _launch(
        tmp_path, 1, "print('unreachable')",
        "--fault-plan", '[{"rank": 0, "op": "Typo", "action": "hang"}]',
    )
    assert res2.returncode == 2
    assert "unknown op" in res2.stderr


# the resume-aware eager training loop the chaos tests drive; saves a
# checkpoint every step (rank 0), prints the final params as hex
_TRAIN = """
import sys
import numpy as np
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.runtime import shm
from mpi4jax_tpu.resilience import CheckpointManager, resume_step

STEPS = 8
rank = shm.rank()
mgr = CheckpointManager(sys.argv[1], keep=3, world=shm.size())
w = jnp.zeros(4)
start = 0
r = resume_step()
if r is not None:
    info = mgr.at_step(r, world=shm.size())
    if info is not None:
        w = mgr.restore(info, {"w": w})["w"]
        start = info.step + 1
        print(f"RESUMED{rank}@{info.step}", file=sys.stderr)
for step in range(start, STEPS):
    g = jnp.full(4, float(step + 1))
    g = m4t.allreduce(g)
    w = w + 0.1 * g
    if rank == 0:
        mgr.save(step, {"w": w})
m4t.barrier()
print(f"FINAL{rank} " + np.asarray(w).tobytes().hex())
"""


def _finals(stdout):
    # two ranks share the captured stdout pipe and their final lines
    # can interleave without newline boundaries; the hex payload is
    # lowercase, so FINAL<rank> markers stay parseable regardless
    return dict(re.findall(r"FINAL(\d) ([0-9a-f]+)", stdout))


@needs_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_crash_resume_bitwise_identical(tmp_path):
    """ISSUE-5 acceptance: rank 1 crashes at its 6th AllReduce
    (step 5) on attempt 0; the supervisor diagnoses a transient crash,
    restarts with --resume-dir, both ranks resume from the latest
    valid checkpoint, and the final parameters are bit-for-bit the
    fault-free run's."""
    pytest.importorskip("orbax.checkpoint")
    clean_ckpt = str(tmp_path / "ckpt_clean")
    res_clean = _launch(
        tmp_path, 2, _TRAIN, script_args=(clean_ckpt,),
    )
    assert res_clean.returncode == 0, res_clean.stderr
    clean = _finals(res_clean.stdout)
    assert set(clean) == {"0", "1"}, res_clean.stdout

    chaos_ckpt = str(tmp_path / "ckpt_chaos")
    rundir = str(tmp_path / "run")
    plan = (tmp_path / "plan.json")
    plan.write_text(json.dumps([{
        "rank": 1, "op": "AllReduce", "nth": 6,
        "action": "crash", "mode": "exception", "attempt": 0,
    }]))
    res = _launch(
        tmp_path, 2, _TRAIN,
        "--events-dir", rundir,
        "--fault-plan", str(plan),
        "--retries", "2", "--backoff", "0.1",
        "--resume-dir", chaos_ckpt,
        script_args=(chaos_ckpt,),
    )
    assert res.returncode == 0, res.stderr
    assert "injecting crash" in res.stderr
    assert "RESUMED0@" in res.stderr and "RESUMED1@" in res.stderr
    assert _finals(res.stdout) == clean  # bit-for-bit
    # audit trail: one failed transient attempt, one clean one
    recs = events.read(os.path.join(rundir, "supervisor.jsonl"))
    assert [r["action"] for r in recs] == ["retry", "done"]
    assert recs[0]["klass"] == "transient"
    assert isinstance(recs[0]["resume_step"], int)
    # the injection is on the record for the doctor/trace overlay
    fault_recs = [
        r
        for r in events.read(
            os.path.join(rundir, "attempt00", "events-rank1.jsonl"))
        if r["kind"] == "fault"
    ]
    assert len(fault_recs) == 1 and fault_recs[0]["action"] == "crash"


# the elastic chaos shape: an eager loop whose state is genuinely
# *sharded* over the world (each rank owns a slice of w), committed
# every step via the two-phase m4t-ckpt/2 protocol. Gradients are
# assembled so each position receives exactly one rank's contribution
# (+ zeros), which makes the final params bit-identical across world
# sizes — the elastic resume has no tolerance to hide behind.
_ELASTIC_TRAIN = """
import sys
import numpy as np
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.runtime import shm
from mpi4jax_tpu.resilience import ckpt, reshard, PreemptGuard, resume_step

STEPS = 8
G = 8
rank, size = shm.rank(), shm.size()
guard = PreemptGuard()
mgr = ckpt.CheckpointManager(sys.argv[1], keep=3, world=size)
specs = {"w": reshard.LeafSpec(shape=(G,), dtype="float32"),
         "s": reshard.LeafSpec(shape=(), dtype="int32",
                               kind="replicated")}
lo, hi = reshard.shard_extent(G, size, rank)
w = np.zeros(hi - lo, np.float32)
start = 0
r = resume_step()
if r is not None:
    info = mgr.at_step(r, world=size)
    if info is not None:
        w = ckpt.load_shard(info, rank)["w"]
        start = info.step + 1
        print(f"RESUMED{rank}@{info.step}", file=sys.stderr)
data = np.arange(G, dtype=np.float32)
for step in range(start, STEPS):
    guard.exit_if_preempted()  # grace: last committed step wins
    part = np.zeros(G, np.float32)
    part[lo:hi] = data[lo:hi] * (step + 1)
    g = np.asarray(m4t.allreduce(jnp.asarray(part)))
    w = w + np.float32(0.1) * g[lo:hi]
    mgr.stage_shard(step, rank, {"w": w, "s": np.int32(step)}, specs)
    m4t.barrier()
    if rank == 0:
        mgr.commit_sharded(step, specs)
    m4t.barrier()
final = np.asarray(m4t.allreduce(jnp.asarray(np.pad(w, (lo, G - hi)))))
print(f"FINAL{rank} " + final.tobytes().hex())
"""


@needs_native
@pytest.mark.chaos
@pytest.mark.elastic
@pytest.mark.slow
def test_elastic_preempt_shrinks_world_and_resumes(tmp_path):
    """ISSUE-9 acceptance: ranks 2 and 3 of a 4-rank world are
    preempted (SIGTERM) at step 5; the elastic supervisor counts the
    survivors, reshards the step-5 checkpoint 4→2, restarts at world
    2, and the final parameters are bit-for-bit the uninterrupted
    2-rank run's. The supervisor audit records the transition and the
    doctor narrates it."""
    # uninterrupted 2-rank reference
    ref_ckpt = str(tmp_path / "ckpt_ref")
    ref = _launch(tmp_path, 2, _ELASTIC_TRAIN, script_args=(ref_ckpt,))
    assert ref.returncode == 0, ref.stderr
    want = _finals(ref.stdout)
    assert set(want) == {"0", "1"}, ref.stdout

    chaos_ckpt = str(tmp_path / "ckpt_chaos")
    rundir = str(tmp_path / "run")
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps([{
        "rank": [2, 3], "op": "AllReduce", "nth": 6,
        "action": "preempt", "attempt": 0,
    }]))
    res = _launch(
        tmp_path, 4, _ELASTIC_TRAIN,
        "--events-dir", rundir,
        "--fault-plan", str(plan),
        "--retries", "2", "--backoff", "0.1",
        "--resume-dir", chaos_ckpt,
        "--elastic", "--min-ranks", "2",
        script_args=(chaos_ckpt,),
        timeout=400,
    )
    assert res.returncode == 0, res.stderr
    assert "injecting preempt" in res.stderr
    assert "preemption signature" in res.stderr
    assert "shrinking world 4 -> 2" in res.stderr
    assert "resharding step" in res.stderr
    assert "RESUMED0@" in res.stderr and "RESUMED1@" in res.stderr
    # bit-for-bit against the 2-rank reference: ranks 0/1 of attempt 1
    got = _finals(res.stdout)
    assert got["0"] == want["0"] and got["1"] == want["1"]
    # audit trail carries the world transition + reshard provenance
    recs = events.read(os.path.join(rundir, "supervisor.jsonl"))
    assert [r["action"] for r in recs] == ["retry", "done"]
    assert recs[0]["reason"] == "preempted"
    assert recs[0]["world"] == 4 and recs[0]["next_world"] == 2
    assert recs[0]["preempted_ranks"] == [2, 3]
    assert isinstance(recs[0]["resharded_from_step"], int)
    assert recs[1]["world"] == 2
    # the resharded checkpoint records its provenance
    from mpi4jax_tpu.resilience.ckpt import CheckpointManager as CM

    info = CM(chaos_ckpt, world=2).latest_valid(world=2)
    assert info is not None and info.world == 2
    steps_seen = CM(chaos_ckpt).steps()
    resharded = CM(chaos_ckpt, world=2).at_step(
        recs[0]["resharded_from_step"], world=2)
    assert resharded is not None, steps_seen
    assert resharded.manifest["resharded_from"]["world"] == 4
    # the doctor narrates the recovery from the attempt artifacts
    from mpi4jax_tpu.observability import doctor

    audit = doctor.load_supervisor_audit(
        [os.path.join(rundir, "attempt00")])
    text = doctor.format_supervisor_timeline(audit)
    assert "ELASTIC: world 4 -> 2" in text


@needs_native
@pytest.mark.chaos
@pytest.mark.elastic
@pytest.mark.slow
def test_elastic_below_min_ranks_gives_up(tmp_path):
    """Fewer survivors than --min-ranks is a give-up, not a smaller
    world: nothing is respawned and the audit says why."""
    ckpt_dir = str(tmp_path / "ckpt")
    rundir = str(tmp_path / "run")
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps([{
        "rank": 1, "op": "AllReduce", "nth": 3,
        "action": "preempt", "attempt": 0,
    }]))
    res = _launch(
        tmp_path, 2, _ELASTIC_TRAIN,
        "--events-dir", rundir,
        "--fault-plan", str(plan),
        "--retries", "2", "--backoff", "0.1",
        "--resume-dir", ckpt_dir,
        "--elastic", "--min-ranks", "2",
        script_args=(ckpt_dir,),
        timeout=400,
    )
    assert res.returncode != 0
    assert "below --min-ranks 2; giving up" in res.stderr
    recs = events.read(os.path.join(rundir, "supervisor.jsonl"))
    assert recs[0]["reason"] == "preempted"
    assert "elastic_blocked" in recs[1]
    # no attempt after the block actually spawned a world
    assert "attempt 1 not spawned" in res.stderr


def test_launch_elastic_flag_validation(tmp_path):
    res = _launch(tmp_path, 1, "print('x')", "--elastic")
    assert res.returncode == 2
    assert "--elastic requires" in res.stderr
    res2 = _launch(tmp_path, 1, "print('x')", "--min-ranks", "2")
    assert res2.returncode == 2
    assert "--min-ranks cannot exceed -n" in res2.stderr


@needs_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_mismatch_is_not_retried(tmp_path):
    """ISSUE-5 acceptance: a MISMATCH-class failure is deterministic —
    the supervisor prints the doctor's diagnosis and gives up with
    retries still in the budget."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        x = m4t.allreduce(jnp.arange(4.0) + r)
        if r == 0:
            m4t.barrier()       # diverges: deadlocks against...
        else:
            m4t.allreduce(x)    # ...rank 1's allreduce at seq 2
        """,
        "--events-dir", rundir, "--retries", "3", "--backoff", "0.1",
        "--hang-timeout", "20",
    )
    assert res.returncode != 0
    assert "MISMATCH at seq 2" in res.stderr
    assert "not retrying" in res.stderr
    recs = events.read(os.path.join(rundir, "supervisor.jsonl"))
    assert len(recs) == 1  # exactly one attempt
    assert recs[0]["klass"] == "deterministic"
    assert recs[0]["action"] == "give_up"
    assert sorted(os.listdir(rundir)) == ["attempt00", "supervisor.jsonl"]
