"""Live telemetry plane (``mpi4jax_tpu/observability/{live,
stream_doctor,export}.py`` + event-log rotation).

Covers the ISSUE-8 acceptance surface:

- torn-line-safe tailing: a partially-written final line is buffered
  (never parsed) until the writer completes it, then parsed exactly
  once; fsync-off sinks are eventually drained;
- ``EventLog`` size-capped rotation (``.1``/``.2`` suffixes) with the
  tailer and the offline readers (``events.read`` -> doctor/perf)
  merging rotated segments transparently;
- streaming-vs-offline doctor verdict parity on the synthetic
  mismatch / hang / straggler fixtures from ``tests/test_doctor.py``;
- the equal-seq *wedged* verdict from ``exec`` records, live and
  post-mortem;
- the closed loop: straggler/anomaly verdicts -> ``retune`` events ->
  ``autotune.keys_from_verdicts`` -> ``planner tune --from-verdicts``;
- OpenMetrics rendering, the atomic ``metrics.prom`` snapshot, and
  the localhost HTTP endpoint;
- end-to-end: ``launch --live`` names a fault-injected hang (rank +
  ``stuck_before``) and exits long before ``--hang-timeout``, with
  the streaming diagnosis matching the offline doctor's; an injected
  slowdown produces a re-pinnable retune recommendation.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from mpi4jax_tpu.observability import doctor, events
from mpi4jax_tpu.observability import export as prom_export
from mpi4jax_tpu.observability.live import (
    LiveAggregator,
    TailReader,
    render_dashboard,
    status_line,
)
from mpi4jax_tpu.observability.stream_doctor import StreamDoctor
from mpi4jax_tpu.planner import autotune
from mpi4jax_tpu.planner import plan as _plan

from tests.test_doctor import (  # noqa: F401 — shared synthetic builders
    clean_world,
    emission,
    heartbeat,
    latency,
    write_logs,
)

pytestmark = [pytest.mark.telemetry, pytest.mark.live]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def exec_rec(rank, seq, op="AllReduce", t=None):
    return {"kind": "exec", "rank": rank, "seq": seq, "op": op,
            "cid": f"c{rank:02d}{seq:04d}", "t": 100.0 + seq if t is None else t}


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def make_stream(tmp_path, *, grace=2.0, platform="cpu"):
    clock = FakeClock()
    agg = LiveAggregator(str(tmp_path), platform=platform, clock=clock)
    sdoc = StreamDoctor(
        agg, grace_s=grace,
        verdict_log=str(tmp_path / "live.jsonl"), clock=clock,
    )
    return clock, agg, sdoc


# ---------------------------------------------------------------------
# torn-line-safe tailing
# ---------------------------------------------------------------------


def test_tail_torn_final_line_buffered_and_parsed_once(tmp_path):
    path = str(tmp_path / "events-rank0.jsonl")
    reader = TailReader(path)
    assert reader.poll() == []  # missing file is not an error
    with open(path, "w") as f:
        f.write(json.dumps(emission(0, 1, "AllReduce", [8], 100.0)) + "\n")
        f.write('{"kind": "emission", "rank": 0, "seq": 2, "op": "AllRe')
    got = reader.poll()
    assert [r["seq"] for r in got] == [1]
    # the torn tail is buffered, not parsed — and not parsed again
    assert reader.poll() == []
    with open(path, "a") as f:
        f.write('duce", "shape": [8], "dtype": "float32"}\n')
    got = reader.poll()
    assert [r["seq"] for r in got] == [2], "completed line parses exactly once"
    assert got[0]["op"] == "AllReduce"
    assert reader.poll() == []


def test_tail_drains_fsync_off_sink(tmp_path):
    """A sink without fsync still closes whole lines per append —
    every record is eventually visible to the tailer."""
    path = str(tmp_path / "events-rank0.jsonl")
    log = events.EventLog(path, fsync=False)
    reader = TailReader(path)
    seen = []
    for i in range(10):
        log.append({"kind": "emission", "rank": 0, "seq": i + 1,
                    "op": "AllReduce"})
        seen.extend(r["seq"] for r in reader.poll())
    assert seen == list(range(1, 11))


def test_tail_skips_malformed_lines(tmp_path):
    path = str(tmp_path / "x.jsonl")
    with open(path, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps({"kind": "emission", "seq": 1}) + "\n")
        f.write("[1, 2, 3]\n")  # JSON but not a record
    assert [r["seq"] for r in TailReader(path).poll()] == [1]


# ---------------------------------------------------------------------
# rotation
# ---------------------------------------------------------------------


def test_eventlog_rotation_caps_and_suffixes(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    log = events.EventLog(path, max_bytes=400)
    for i in range(40):
        log.append({"kind": "emission", "rank": 0, "seq": i + 1,
                    "op": "AllReduce", "bytes": 64})
    log.close()
    # the live path always exists after an append (rotation recreates
    # it) — the layout contract the doctor's *.jsonl glob relies on
    for p in (path, path + ".1", path + ".2"):
        assert os.path.exists(p), p
        assert os.path.getsize(p) <= 400 + 200  # cap + one record slack
    # merged read: contiguous suffix of the stream, oldest first
    seqs = [r["seq"] for r in events.read(path)]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 40
    assert len(seqs) >= 3  # at least the three on-disk segments' worth


def test_tail_reader_never_loses_or_dupes_across_rotation(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    log = events.EventLog(path, max_bytes=512)
    reader = TailReader(path)
    seen = []
    for i in range(60):
        log.append({"kind": "emission", "rank": 0, "seq": i + 1,
                    "op": "AllReduce", "bytes": 64})
        if i % 5 == 0:
            seen.extend(r["seq"] for r in reader.poll())
    log.close()
    seen.extend(r["seq"] for r in reader.poll())
    assert seen == list(range(1, 61)), seen


def test_doctor_merges_rotated_sinks(tmp_path):
    """The offline doctor (and everything on doctor.load: perf,
    measured tables) sees rotated segments as one stream."""
    for rank in (0, 1):
        log = events.EventLog(
            str(tmp_path / f"events-rank{rank}.jsonl"), max_bytes=300
        )
        for s in range(1, 21):
            log.append(emission(rank, s, "AllReduce", [8], 100.0 + s))
        log.close()
    assert os.path.exists(str(tmp_path / "events-rank0.jsonl.1"))
    report = doctor.diagnose([str(tmp_path)])
    assert report["seqs"] == {"0": 20, "1": 20}
    assert report["findings"] == []


# ---------------------------------------------------------------------
# streaming-vs-offline verdict parity (the test_doctor fixtures)
# ---------------------------------------------------------------------


def _confirmed_findings(sdoc):
    return [v["finding"] for v in sdoc.confirmed]


def test_streaming_matches_offline_on_mismatch(tmp_path):
    logs = clean_world(n_ranks=3)
    logs[2][2] = emission(2, 3, "AllGather", [8], 103.0)
    write_logs(tmp_path, logs)
    clock, agg, sdoc = make_stream(tmp_path)
    sdoc.check()
    offline = doctor.diagnose([str(tmp_path)])
    mismatches = [f for f in offline["findings"] if f["kind"] == "mismatch"]
    # confirmed immediately — no stall grace for deterministic evidence
    assert [
        f for f in _confirmed_findings(sdoc) if f["kind"] == "mismatch"
    ] == mismatches
    assert sdoc.escalation_report is not None
    (v,) = [v for v in sdoc.confirmed if v["finding"]["kind"] == "mismatch"]
    assert v["klass"] == "deterministic"


def test_streaming_matches_offline_on_hang_after_grace(tmp_path):
    logs = clean_world(n_ranks=4, n_seq=5)
    logs[1] = logs[1][:2] + [heartbeat(1, 130.0)]
    logs[2] = logs[2][:2] + [heartbeat(2, 102.0)]
    logs[3] = logs[3][:2]
    write_logs(tmp_path, logs)
    clock, agg, sdoc = make_stream(tmp_path, grace=2.0)
    sdoc.check()
    assert sdoc.escalation_report is None, "no hang before the stall grace"
    assert _confirmed_findings(sdoc) == []
    clock.advance(5.0)  # world stalls past the grace
    sdoc.check()
    offline = doctor.diagnose([str(tmp_path)])
    hangs = {f["rank"]: f for f in offline["findings"] if f["kind"] == "hang"}
    confirmed = {
        f["rank"]: f for f in _confirmed_findings(sdoc) if f["kind"] == "hang"
    }
    assert confirmed == hangs
    assert confirmed[1]["verdict"] == "hung"
    assert confirmed[2]["verdict"] == "dead"
    assert confirmed[3]["verdict"] == "behind"
    for v in sdoc.confirmed:
        assert v["klass"] == "transient"
    assert sdoc.escalation_report["schema"] == "m4t-doctor/1"


def test_streaming_progress_resets_the_stall_clock(tmp_path):
    logs = clean_world(n_seq=4)
    logs[1] = logs[1][:2]
    write_logs(tmp_path, logs)
    clock, agg, sdoc = make_stream(tmp_path, grace=3.0)
    sdoc.check()
    clock.advance(2.0)
    # rank 1 catches up just before the grace expires
    with open(tmp_path / "events-rank1.jsonl", "a") as f:
        f.write(json.dumps(emission(1, 3, "AllReduce", [8], 103.0)) + "\n")
        f.write(json.dumps(emission(1, 4, "AllReduce", [8], 104.0)) + "\n")
    sdoc.check()
    clock.advance(2.0)  # stall clock restarted by the new records
    sdoc.check()
    assert sdoc.escalation_report is None
    assert _confirmed_findings(sdoc) == []


def test_streaming_matches_offline_on_straggler(tmp_path):
    logs = clean_world(n_ranks=4)
    for r in range(4):
        per = 0.05 if r == 3 else 0.001
        for i in range(5):
            logs[r].append(latency(r, "AllReduce", per, 105.0 + i))
    write_logs(tmp_path, logs)
    clock, agg, sdoc = make_stream(tmp_path)
    sdoc.check()
    offline = [f for f in doctor.diagnose([str(tmp_path)])["findings"]
               if f["kind"] == "straggler"]
    confirmed = [f for f in _confirmed_findings(sdoc)
                 if f["kind"] == "straggler"]
    assert confirmed == offline and confirmed[0]["rank"] == 3
    # stragglers never escalate (transient, the run may still finish)
    assert sdoc.escalation_report is None
    # ...and are confirmed only once across re-checks
    sdoc.check()
    clock.advance(10.0)
    sdoc.check()
    assert len([f for f in _confirmed_findings(sdoc)
                if f["kind"] == "straggler"]) == len(offline)


# ---------------------------------------------------------------------
# the equal-seq wedge verdict (exec records)
# ---------------------------------------------------------------------


def wedged_world(tmp_path):
    """Both ranks record seqs 1..3; rank 0 began executing all three,
    rank 1 never entered seq 3 (its heartbeats continue)."""
    logs = clean_world(n_ranks=2, n_seq=3)
    logs[0] += [exec_rec(0, s) for s in (1, 2, 3)]
    logs[1] += [exec_rec(1, s) for s in (1, 2)]
    logs[1].append(heartbeat(1, 150.0))
    return write_logs(tmp_path, logs)


def test_offline_doctor_names_wedged_rank(tmp_path):
    d = wedged_world(tmp_path)
    report = doctor.diagnose([d])
    (f,) = [x for x in report["findings"] if x["kind"] == "hang"]
    assert f["wedged"] is True
    assert f["rank"] == 1 and f["verdict"] == "hung"
    assert f["last_seq"] == f["front_seq"] == 3 and f["gap"] == 0
    assert f["front_ranks"] == [0]
    assert f["stuck_before"] == "AllReduce[8:float32]@ranks"
    text = doctor.format_report(report)
    assert "never began executing" in text
    assert "stuck before: AllReduce[8:float32]@ranks" in text


def test_wedge_needs_peer_exec_evidence(tmp_path):
    """No rank entered the front seq -> no culprit to name (could be
    a mismatch's rendezvous failure or plain slowness)."""
    logs = clean_world(n_ranks=2, n_seq=3)
    logs[0] += [exec_rec(0, s) for s in (1, 2)]
    logs[1] += [exec_rec(1, s) for s in (1, 2)]
    write_logs(tmp_path, logs)
    assert doctor.diagnose([str(tmp_path)])["findings"] == []


def test_wedge_needs_own_earlier_exec_evidence(tmp_path):
    """A rank with no exec records at all (callbacks unsupported /
    sampling off) is never branded wedged."""
    logs = clean_world(n_ranks=2, n_seq=3)
    logs[0] += [exec_rec(0, s) for s in (1, 2, 3)]
    write_logs(tmp_path, logs)
    assert doctor.diagnose([str(tmp_path)])["findings"] == []


def test_completed_world_is_not_wedged(tmp_path):
    logs = clean_world(n_ranks=2, n_seq=3)
    for r in (0, 1):
        logs[r] += [exec_rec(r, s) for s in (1, 2, 3)]
    write_logs(tmp_path, logs)
    assert doctor.diagnose([str(tmp_path)])["findings"] == []


def test_streaming_wedge_confirms_after_stall_and_matches_offline(tmp_path):
    d = wedged_world(tmp_path)
    clock, agg, sdoc = make_stream(tmp_path, grace=2.0)
    sdoc.check()
    assert sdoc.escalation_report is None
    clock.advance(3.0)
    sdoc.check()
    rep = sdoc.escalation_report
    assert rep is not None
    offline = doctor.diagnose([d])
    assert rep["findings"] == [
        f for f in offline["findings"] if f["kind"] == "hang"
    ]


# ---------------------------------------------------------------------
# retune recommendations (the closed loop)
# ---------------------------------------------------------------------


def straggler_world_with_payloads(tmp_path):
    logs = {}
    for r in range(2):
        logs[r] = [
            emission(r, s, "AllReduce", [1024], 100.0 + s, nbytes=4096)
            for s in range(1, 4)
        ]
        per = 0.05 if r == 1 else 0.001
        logs[r] += [latency(r, "AllReduce", per, 104.0 + i)
                    for i in range(6)]
    return write_logs(tmp_path, logs)


def test_straggler_confirmation_emits_retune_with_plan_keys(tmp_path):
    straggler_world_with_payloads(tmp_path)
    clock, agg, sdoc = make_stream(tmp_path)
    sdoc.check()
    retunes = [r for r in events.read(str(tmp_path / "live.jsonl"))
               if r["kind"] == "retune"]
    assert len(retunes) == 1
    rt = retunes[0]
    assert rt["reason"] == "straggler" and rt["op"] == "AllReduce"
    assert rt["plan_keys"], rt
    for key in rt["plan_keys"]:
        info = _plan.parse_key(key)  # well-formed by contract
        assert info["op"] == "AllReduce" and info["world"] == 2
    # retune events are deduped across re-checks
    sdoc.check()
    assert len([r for r in events.read(str(tmp_path / "live.jsonl"))
                if r["kind"] == "retune"]) == 1


def test_anomaly_records_become_retune_events(tmp_path):
    logs = clean_world()
    logs[0].append({
        "kind": "anomaly", "rank": 0, "op": "AllReduce",
        "key": "AllReduce[8:float32]@ranks", "seconds": 0.5,
        "baseline_s": 0.001, "z": 40.0, "bytes": 4096,
        "dtype": "float32", "axes": ["ranks"], "world": 2, "t": 109.0,
    })
    write_logs(tmp_path, logs)
    clock, agg, sdoc = make_stream(tmp_path)
    sdoc.check()
    (rt,) = [r for r in events.read(str(tmp_path / "live.jsonl"))
             if r["kind"] == "retune"]
    assert rt["reason"] == "anomaly"
    assert rt["plan_keys"] == [
        _plan.plan_key("AllReduce", nbytes=4096, dtype="float32",
                       world=2, axes=("ranks",), platform="cpu")
    ]


def test_keys_from_verdicts_reads_validates_and_dedupes(tmp_path):
    log = events.EventLog(str(tmp_path / "live.jsonl"))
    good = "AllReduce|b13|float32|w2|ranks|cpu"
    other_platform = "AllReduce|b13|float32|w2|ranks|tpu:v5e"
    log.append({"kind": "retune", "reason": "straggler",
                "plan_keys": [good, "garbage-key", other_platform]})
    log.append({"kind": "retune", "reason": "anomaly",
                "plan_keys": [good]})
    log.close()
    assert autotune.keys_from_verdicts(
        [str(tmp_path)], platform="cpu"
    ) == [good]
    # platform=None keeps every well-formed key
    assert autotune.keys_from_verdicts([str(tmp_path)]) == [
        good, other_platform
    ]
    assert autotune.keys_from_verdicts([str(tmp_path / "nope")]) == []
    # the keys feed the sweep directly
    planobj, _ = autotune.sweep([good])
    assert good in planobj.entries


def _run_cli(module, *argv, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", module, *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
    )


def test_planner_tune_from_verdicts_cli(tmp_path):
    straggler_world_with_payloads(tmp_path)
    clock, agg, sdoc = make_stream(tmp_path)
    sdoc.check()  # writes the retune event into live.jsonl
    cache = str(tmp_path / "plan.json")
    res = _run_cli("mpi4jax_tpu.planner", "tune",
                   "--from-verdicts", str(tmp_path),
                   "--cache", cache, "--platform", "cpu")
    assert res.returncode == 0, res.stderr
    assert "recommended by live verdicts" in res.stderr
    planobj = _plan.load(cache, platform="cpu")
    keys = autotune.keys_from_verdicts([str(tmp_path)], platform="cpu")
    assert keys and set(keys) <= set(planobj.entries)

    # no recommendations -> exit 2, cache untouched
    empty = tmp_path / "empty"
    empty.mkdir()
    res = _run_cli("mpi4jax_tpu.planner", "tune",
                   "--from-verdicts", str(empty), "--cache", cache,
                   "--platform", "cpu")
    assert res.returncode == 2
    assert "no retune events" in res.stderr


# ---------------------------------------------------------------------
# aggregator snapshot / dashboard / OpenMetrics
# ---------------------------------------------------------------------


def test_aggregator_snapshot_state(tmp_path):
    logs = clean_world(n_ranks=2, n_seq=4)
    logs[0].append(heartbeat(0, time.time()))
    write_logs(tmp_path, logs)
    agg = LiveAggregator(str(tmp_path), platform="cpu")
    assert agg.poll() > 0
    assert agg.poll() == 0  # drained
    snap = agg.snapshot()
    assert snap["ranks"] == [0, 1]
    assert snap["seqs"] == {"0": 4, "1": 4}
    assert snap["seq_skew"] == 0
    assert "AllReduce|-" in snap["totals"]
    assert snap["totals"]["AllReduce|-"]["emissions"] == 8
    assert snap["heartbeat_age_s"]["0"] >= 0
    key = _plan.plan_key("AllReduce", nbytes=16, dtype="float32",
                         world=2, axes=("ranks",), platform="cpu")
    assert snap["plan_keys"][key]["emissions"] == 8
    dash = render_dashboard(snap)
    assert "rank" in dash and "AllReduce" in dash
    line = status_line(snap)
    assert "r0:4" in line and "skew 0" in line


def test_openmetrics_render_contract(tmp_path):
    write_logs(tmp_path, clean_world())
    agg = LiveAggregator(str(tmp_path), platform="cpu")
    agg.poll()
    verdicts = [{"kind": "verdict", "klass": "transient",
                 "finding": {"kind": "hang", "rank": 1}}]
    text = prom_export.render_openmetrics(agg.snapshot(), verdicts=verdicts)
    assert text.endswith("# EOF\n")
    lines = text.splitlines()
    assert 'm4t_rank_last_seq{rank="0"} 4' in lines
    assert 'm4t_emissions_total{impl="-",op="AllReduce"} 8' in lines
    assert 'm4t_verdicts_total{kind="hang",klass="transient"} 1' in lines
    # TYPE precedes every family's samples
    seen_types = set()
    for ln in lines:
        if ln.startswith("# TYPE"):
            seen_types.add(ln.split()[2])
        elif ln and not ln.startswith("#"):
            name = ln.split("{")[0].split(" ")[0]
            assert name in seen_types, f"sample before its TYPE: {ln}"


def test_openmetrics_label_escaping():
    text = prom_export.render_openmetrics({
        "ranks": [0], "records": 1, "seqs": {"0": 1}, "seq_skew": 0,
        "stalled_s": None, "heartbeat_age_s": {}, "emission_age_s": {},
        "totals": {'Op"quoted\\|x': {"emissions": 1, "payload_bytes": 2}},
        "plan_keys": {}, "rates": {}, "anomalies": 0,
    })
    assert 'op="Op\\"quoted\\\\"' in text


def test_write_prom_is_atomic_and_replaces(tmp_path):
    path = str(tmp_path / "metrics.prom")
    prom_export.write_prom(path, "# EOF\n")
    assert open(path).read() == "# EOF\n"
    prom_export.write_prom(path, "m4t_live_ranks 2\n# EOF\n")
    assert open(path).read().startswith("m4t_live_ranks")
    leftovers = [p for p in os.listdir(str(tmp_path))
                 if p.startswith(".prom-")]
    assert leftovers == []


def test_http_metrics_endpoint(tmp_path):
    payload = {"text": "m4t_live_ranks 2\n# EOF\n"}
    server = prom_export.serve(lambda: payload["text"], port=0)
    try:
        base = f"http://127.0.0.1:{server.server_port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.status == 200
            assert "openmetrics-text" in resp.headers["Content-Type"]
            assert resp.read().decode() == payload["text"]
        payload["text"] = "m4t_live_ranks 4\n# EOF\n"  # live re-render
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert b"4" in resp.read()
        try:
            urllib.request.urlopen(f"{base}/other", timeout=10)
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("non-/metrics paths must 404")
    finally:
        server.shutdown()


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def test_live_cli_selftest():
    res = _run_cli("mpi4jax_tpu.observability.live", "--selftest")
    assert res.returncode == 0, res.stderr + res.stdout
    assert "live selftest ok" in res.stdout


def test_live_cli_snapshot_and_json(tmp_path):
    write_logs(tmp_path, clean_world())
    res = _run_cli("mpi4jax_tpu.observability.live", str(tmp_path))
    assert res.returncode == 0, res.stderr
    assert "m4t live" in res.stdout and "AllReduce" in res.stdout
    res = _run_cli("mpi4jax_tpu.observability.live", str(tmp_path), "--json")
    assert res.returncode == 0, res.stderr
    obj = json.loads(res.stdout)
    assert obj["snapshot"]["seqs"] == {"0": 4, "1": 4}
    assert obj["verdicts"] == []


def test_live_cli_writes_prom(tmp_path):
    write_logs(tmp_path, clean_world())
    out = str(tmp_path / "m.prom")
    res = _run_cli("mpi4jax_tpu.observability.live", str(tmp_path),
                   "--prom", out)
    assert res.returncode == 0, res.stderr
    assert open(out).read().endswith("# EOF\n")


# ---------------------------------------------------------------------
# end-to-end: real 2-rank launcher worlds on CPU (slow-marked)
# ---------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


def _launch(tmp_path, n, script, *launch_args, timeout=180):
    import textwrap

    path = str(tmp_path / "case.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n),
         *launch_args, path],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


LOOP_SCRIPT = """
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.runtime import shm
x = jnp.arange(1024.0) + shm.rank()
for i in range({n}):
    x = m4t.allreduce(x) * 0.5
    float(x[0])
print("DONE", shm.rank(), flush=True)
"""


@needs_native
@pytest.mark.slow
def test_launch_live_escalates_fault_hang_before_watchdog(tmp_path):
    """Acceptance: under a --fault-plan injected hang the streaming
    doctor names the hung rank and its stuck_before collective and
    the launcher exits *long before* --hang-timeout, with a diagnosis
    the offline doctor agrees with."""
    rundir = str(tmp_path / "run")
    start = time.monotonic()
    res = _launch(
        tmp_path, 2, LOOP_SCRIPT.format(n=6),
        "--events-dir", rundir, "--live", "--live-grace", "3",
        "--heartbeat", "1", "--hang-timeout", "120",
        "--fault-plan",
        '[{"rank": 1, "op": "AllReduce", "nth": 3, "action": "hang"}]',
    )
    elapsed = time.monotonic() - start
    assert res.returncode == 124, (res.returncode, res.stderr)
    assert elapsed < 60, f"escalation took {elapsed:.0f}s (watchdog is 120s)"
    assert "streaming doctor confirmed a verdict" in res.stderr
    assert "rank 1 recorded seq 3 but never began executing it" in res.stderr
    assert "stuck before: AllReduce" in res.stderr
    # the offline doctor reaches the same verdict from the artifacts
    (f,) = [x for x in doctor.diagnose([rundir])["findings"]
            if x["kind"] == "hang"]
    assert f["rank"] == 1 and f["wedged"] and f["last_seq"] == 3
    assert f["stuck_before"].startswith("AllReduce")
    # the same diagnosis was printed as the exit post-mortem
    assert "post-mortem diagnosis" in res.stderr
    # verdict event recorded with the supervisor's classification
    (v,) = [r for r in events.read(os.path.join(rundir, "live.jsonl"))
            if r["kind"] == "verdict"]
    assert v["klass"] == "transient" and v["finding"]["rank"] == 1
    # and the exporter left a final scrape behind
    prom = open(os.path.join(rundir, "metrics.prom")).read()
    assert prom.endswith("# EOF\n") and 'm4t_rank_last_seq{rank="1"} 3' in prom


@needs_native
@pytest.mark.slow
def test_launch_live_slowdown_yields_retune_that_repins(tmp_path):
    """Acceptance: an injected slowdown produces a retune event whose
    plan keys `tune --from-verdicts` accepts and re-pins."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 2, LOOP_SCRIPT.format(n=12),
        "--events-dir", rundir, "--live", "--heartbeat", "1",
        "--fault-plan",
        '[{"rank": 1, "op": "AllReduce", "nth": 1, '
        '"action": "slowdown", "ms": 40}]',
    )
    assert res.returncode == 0, res.stderr
    retunes = [r for r in events.read(os.path.join(rundir, "live.jsonl"))
               if r["kind"] == "retune"]
    assert retunes, "slowdown must produce a retune recommendation"
    assert retunes[0]["reason"] == "straggler"
    assert retunes[0]["op"] == "AllReduce" and retunes[0]["plan_keys"]
    cache = str(tmp_path / "plan.json")
    cli = _run_cli("mpi4jax_tpu.planner", "tune", "--from-verdicts",
                   rundir, "--cache", cache, "--platform", "cpu")
    assert cli.returncode == 0, cli.stderr
    planobj = _plan.load(cache, platform="cpu")
    for key in retunes[0]["plan_keys"]:
        assert key in planobj.entries, f"{key} not re-pinned"
