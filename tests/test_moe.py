"""Expert-parallel MoE: the distributed dispatch/combine over alltoall
must reproduce the single-device computation (each token processed by
its routed expert, gate-weighted), and train end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.parallel import moe

N = 8
T = 16   # tokens per rank
D = 8
FF = 16


@pytest.fixture()
def weights():
    rng = np.random.RandomState(0)
    router = rng.randn(D, N).astype(np.float32) * 0.5
    w_up = rng.randn(N, D, FF).astype(np.float32) / np.sqrt(D)
    w_down = rng.randn(N, FF, D).astype(np.float32) / np.sqrt(FF)
    return router, w_up, w_down


def reference_moe(x, router, w_up, w_down, capacity):
    """Single-process oracle with the same routing + capacity rules."""
    probs = jax.nn.softmax(jnp.asarray(x) @ router, axis=-1)
    probs = np.asarray(probs)
    expert = probs.argmax(-1)
    gate = probs.max(-1)
    out = np.zeros_like(x)
    counts = {e: 0 for e in range(N)}
    for i, (e, g) in enumerate(zip(expert, gate)):
        if counts[e] >= capacity:
            continue
        counts[e] += 1
        h = np.asarray(jax.nn.gelu(jnp.asarray(x[i] @ w_up[e])))
        out[i] = (h @ w_down[e]) * g
    return out


def test_moe_matches_single_device(run_spmd, weights):
    router, w_up, w_down = weights
    rng = np.random.RandomState(1)
    x_all = rng.randn(N, T, D).astype(np.float32)
    capacity = max(int(2.0 * T / N), 1)

    def f(x, wu, wd):
        y, kept = moe.moe_ffn(
            x, jnp.asarray(router), wu, wd, capacity_factor=2.0
        )
        return y, kept * jnp.ones(())

    out, kept = run_spmd(f, jnp.asarray(x_all), jnp.asarray(w_up), jnp.asarray(w_down))

    # oracle: per source rank, tokens routed independently but capacity
    # applies per (rank, expert) pair locally before dispatch
    for r in range(N):
        expected = reference_moe(x_all[r], jnp.asarray(router), w_up, w_down, capacity)
        np.testing.assert_allclose(out[r], expected, rtol=2e-4, atol=2e-5)
    assert kept.min() > 0.3  # sane routing, not all dropped


def test_moe_differentiable(run_spmd, weights):
    router, w_up, w_down = weights
    rng = np.random.RandomState(2)
    x_all = rng.randn(N, T, D).astype(np.float32)

    def f(x, wu, wd):
        def loss(wu_):
            y, _ = moe.moe_ffn(x, jnp.asarray(router), wu_, wd)
            return (y ** 2).sum()

        g = jax.grad(loss)(wu)
        return g

    grads = run_spmd(f, jnp.asarray(x_all), jnp.asarray(w_up), jnp.asarray(w_down))
    assert np.isfinite(grads).all()
    # the gradient must be nonzero for experts that received tokens
    assert np.abs(grads).sum() > 0
