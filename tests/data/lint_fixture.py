"""Fixed lint-fixture module for the golden JSON schema pin.

tests/test_analysis_selflint.py lints this module's targets and
compares the full JSON report against tests/data/lint_golden.json
(same pattern as the Perfetto trace_golden.json pin): any schema
drift must be an intentional, reviewed change — regenerate with

    python tests/test_analysis_selflint.py --regen

Do not edit casually: source line numbers of this file are part of
the pinned output.
"""

N = 4


def _target_clean():
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    def step(x):
        y = m4t.allreduce(x)
        return m4t.allgather(y)

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": N},
    )


def _target_divergent():
    import jax
    import jax.numpy as jnp
    from jax import lax

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    def step(x):
        r = lax.axis_index("ranks")
        y = lax.cond(
            r == 0, lambda v: m4t.allreduce(v), lambda v: v, x
        )
        return m4t.allreduce(y.astype(jnp.bfloat16))

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": N},
    )


M4T_LINT_TARGETS = {
    "clean": _target_clean,
    "divergent": _target_divergent,
}
