"""Fixed simulate-fixture module for the golden JSON schema pin.

tests/test_analysis_simulate.py verifies this module's targets
(``--simulate --json`` schema) and compares the full JSON report
against tests/data/simulate_golden.json (same pattern as
lint_golden.json / trace_golden.json): any schema drift must be an
intentional, reviewed change — regenerate with

    python tests/test_analysis_simulate.py --regen

Do not edit casually: source line numbers of this file are part of
the pinned output.
"""

N = 4


def _target_clean(world: int = N):
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    def step(x):
        y = m4t.allreduce(x)
        return m4t.allgather(y)

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": world},
    )


def _target_crossed(world: int = 2):
    """Crossed unbuffered sendrecv: even ranks send right while odd
    ranks send left — the canonical M4T201 deadlock."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    n = world

    def step(x):
        r = lax.axis_index("ranks")

        def evens(v):
            dest = tuple((k + 1) if k % 2 == 0 else -1 for k in range(n))
            src = tuple((k - 1) if k % 2 == 1 else -1 for k in range(n))
            return m4t.sendrecv(v, v, src, dest, sendtag=1)

        def odds(v):
            dest = tuple((k - 1) if k % 2 == 1 else -1 for k in range(n))
            src = tuple((k + 1) if k % 2 == 0 else -1 for k in range(n))
            return m4t.sendrecv(v, v, src, dest, sendtag=1)

        return lax.cond(r % 2 == 0, evens, odds, x)

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": n},
    )


def _target_mismatch(world: int = 2):
    """Rank 0 enters an AllReduce while every other rank enters an
    AllGather: the doctor's runtime MISMATCH, statically (M4T202)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    def step(x):
        r = lax.axis_index("ranks")
        return lax.cond(
            r == 0,
            lambda v: m4t.allreduce(v),
            lambda v: m4t.allgather(v)[0] * 1.0,
            x,
        )

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": world},
    )


def _target_redundant(world: int = N):
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.analysis import LintTarget

    def step(x):
        return m4t.allreduce(m4t.allreduce(x))

    return LintTarget(
        fn=step,
        args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        axis_env={"ranks": world},
    )


M4T_LINT_TARGETS = {
    "clean": _target_clean,
    "crossed": _target_crossed,
    "mismatch": _target_mismatch,
    "redundant": _target_redundant,
}
