"""Native shared-memory backend tests, run through the real launcher
in subprocesses — the reference's crash-path/subprocess technique
(``tests/collective_ops/test_common.py:13-57`` run_in_subprocess) plus
its ``mpirun -np N pytest`` execution model, with
``python -m mpi4jax_tpu.launch`` in mpirun's role."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def launch(n, script, env_extra=None, timeout=120):
    path = os.path.join(os.environ.get("TMPDIR", "/tmp"), f"m4t_case_{os.getpid()}.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children don't need the 8-device trick
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n), path],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


@needs_native
def test_world_collectives():
    res = launch(
        4,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        x = jnp.arange(4.0) + r
        assert np.allclose(m4t.allreduce(x, op=m4t.SUM),
                           np.arange(4.0) * n + sum(range(n)))
        assert np.allclose(m4t.allgather(jnp.float32(r)), np.arange(n))
        assert float(m4t.scan(jnp.float32(r), op=m4t.SUM)) == sum(range(r + 1))
        m4t.barrier()
        print(f"OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(4):
        assert f"OK{r}" in res.stdout


@needs_native
def test_rank_divergent_send_recv():
    # The reference's deadlock-ordering pattern
    # (test_send_and_recv.py:91-110): asymmetric send/recv order across
    # ranks — expressible here because the shm backend is
    # multi-controller like the reference.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        x = jnp.full(3, float(r))
        if r == 0:
            m4t.send(x, dest=1, tag=1)
            got = m4t.recv(jnp.zeros(3), source=1, tag=2)
            assert np.allclose(got, 1.0)
        else:
            got = m4t.recv(jnp.zeros(3), source=0, tag=1)
            m4t.send(x, dest=0, tag=2)
            assert np.allclose(got, 0.0)
        print(f"P2P_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "P2P_OK0" in res.stdout and "P2P_OK1" in res.stdout


@needs_native
def test_large_message_chunking():
    # > 4 MiB collective slot and > 256 KiB p2p entry force the chunked
    # protocols.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        big = jnp.arange(3_000_000, dtype=jnp.float32) + r  # ~12 MB
        out = m4t.allreduce(big, op=m4t.SUM)
        assert np.allclose(out[:5], 2 * np.arange(5) + 1)
        partner = 1 - r
        sw = m4t.sendrecv(big, jnp.zeros_like(big), source=partner, dest=partner)
        assert float(sw[0]) == float(partner)
        print(f"BIG_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "BIG_OK0" in res.stdout and "BIG_OK1" in res.stdout


@needs_native
def test_abort_propagates():
    # Fail-fast parity (reference abort_on_error -> MPI_Abort,
    # tested via subprocess at test_common.py:60-88): one rank dying
    # must take the world down with a nonzero exit.
    res = launch(
        2,
        """
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        if shm.rank() == 1:
            raise SystemExit(7)
        import jax.numpy as jnp
        m4t.barrier()  # would hang forever without abort detection
        """,
        timeout=180,
    )
    assert res.returncode != 0
    assert "terminating world" in res.stderr


@needs_native
def test_debug_log_format():
    # Debug-log contract (reference test_common.py:118-146): rank
    # prefix, 8-char correlation id, op name, "done" with timing.
    res = launch(
        2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        m4t.allreduce(jnp.ones(4), op=m4t.SUM)
        """,
        env_extra={"MPI4JAX_TPU_DEBUG": "1"},
    )
    assert res.returncode == 0, res.stderr
    import re

    assert re.search(
        r"shmcc r[01] \| [a-z0-9]{8} \| Allreduce done \(\d\.\d{2}e[+-]\d+ s\)",
        res.stderr,
    ), res.stderr


@needs_native
def test_abi_info():
    from mpi4jax_tpu.runtime import shm
    from mpi4jax_tpu.runtime.shm_group import _TAG_BASE

    info = shm.abi_info()
    assert info["max_ranks"] >= 2
    assert info["coll_chunk_bytes"] >= 1 << 20
    # the reserved group-collective tag namespace must agree between
    # the native wildcard exclusions (kTagBase) and the Python layer,
    # or wildcard matching stops protecting group traffic
    assert info["tag_base"] == _TAG_BASE


@needs_native
def test_status_and_any_source():
    # MPI.Status capture + ANY_SOURCE wildcard (reference
    # recv.py:49-54,100-103) — expressible only in the multi-controller
    # shm world.
    res = launch(
        3,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        if r == 0:
            seen = set()
            for _ in range(2):
                st = m4t.Status()
                got = m4t.recv(jnp.zeros(4), m4t.ANY_SOURCE, status=st)
                assert st.Get_source() in (1, 2), st
                assert st.Get_tag() == 40 + st.Get_source(), st
                assert st.Get_count(np.float32) == 4, st
                assert float(got[0]) == float(st.Get_source())
                seen.add(st.Get_source())
            assert seen == {1, 2}, seen
        elif r in (1, 2):
            m4t.send(jnp.full(4, float(r)), dest=0, tag=40 + r)
        # fence the wildcard phase: a tag-77 message in flight during
        # it would match rank 0's ANY_TAG wildcard recv (size mismatch)
        m4t.barrier()
        if r == 0:
            # explicit-source recv also fills the status
            st2 = m4t.Status()
            got = m4t.recv(jnp.zeros(2), 1, tag=77, status=st2)
            assert (st2.source, st2.tag) == (1, 77), st2
        elif r == 1:
            m4t.send(jnp.ones(2), dest=0, tag=77)
        m4t.barrier()
        print(f"STATUS_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(3):
        assert f"STATUS_OK{r}" in res.stdout


@needs_native
def test_root_only_gather_scatter():
    # Exact reference shapes (gather.py:80-89, scatter.py:145-153):
    # root gets/passes the stacked array, non-root ranks work with
    # block-shaped arrays and gather returns their input unchanged.
    res = launch(
        4,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        x = jnp.arange(3.0) + 10 * r
        g = m4t.gather(x, root=1)
        if r == 1:
            assert g.shape == (n, 3), g.shape
            assert np.allclose(np.asarray(g), np.arange(3.0) + 10 * np.arange(n)[:, None])
        else:
            assert g.shape == (3,), g.shape
            assert np.allclose(np.asarray(g), np.asarray(x))
        # scatter: root passes (n, block), others pass a block template
        if r == 2:
            full = jnp.arange(float(n * 2)).reshape(n, 2)
            s = m4t.scatter(full, root=2)
        else:
            s = m4t.scatter(jnp.zeros(2), root=2)
        assert s.shape == (2,), s.shape
        assert np.allclose(np.asarray(s), [2.0 * r, 2.0 * r + 1])
        print(f"ROOTONLY_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(4):
        assert f"ROOTONLY_OK{r}" in res.stdout


@needs_native
def test_complex_reductions():
    # c64/c128 SUM/PROD on the native reduction path (reference dtype
    # table _src/utils.py:101-128); MAX raises in Python before the
    # native layer can abort.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        z64 = jnp.asarray([1 + 1j * r, 2 - 1j * r], jnp.complex64)
        s = m4t.allreduce(z64, op=m4t.SUM)
        assert np.allclose(np.asarray(s), [2 + 1j, 4 - 1j]), s
        z128 = jnp.asarray([1 + 1j * (r + 1)], jnp.complex128)
        p = m4t.allreduce(z128, op=m4t.PROD)
        assert np.allclose(np.asarray(p), [(1 + 1j) * (1 + 2j)]), p
        try:
            m4t.allreduce(z64, op=m4t.MAX)
            raise SystemExit("complex MAX should have raised")
        except NotImplementedError:
            pass
        print(f"COMPLEX_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "COMPLEX_OK0" in res.stdout and "COMPLEX_OK1" in res.stdout


@needs_native
def test_comm_split_on_launcher_world():
    # MPI_Comm_split reachability on the shm backend: collectives and
    # p2p on each sub-communicator stay inside the group.
    res = launch(
        4,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        comm = m4t.Comm().Split([0, 0, 1, 1])  # {0,1} and {2,3}
        gr = r % 2               # rank within the group
        base = (r // 2) * 2      # group leader's global rank
        # allreduce stays inside the group
        s = m4t.allreduce(jnp.float32(r), op=m4t.SUM, comm=comm)
        assert float(s) == (base) + (base + 1), (r, float(s))
        # bcast from group root 1
        b = m4t.bcast(jnp.float32(r), 1, comm=comm)
        assert float(b) == base + 1, (r, float(b))
        # allgather within the group
        ag = m4t.allgather(jnp.float32(r), comm=comm)
        assert np.allclose(np.asarray(ag), [base, base + 1]), (r, ag)
        # scan within the group
        sc = m4t.scan(jnp.float32(r), op=m4t.SUM, comm=comm)
        assert float(sc) == (base if gr == 0 else 2 * base + 1), (r, float(sc))
        # p2p ring inside the group (group-rank tables)
        sw = m4t.sendrecv(jnp.float32(r), jnp.float32(0),
                          source=[1, 0], dest=[1, 0], comm=comm)
        assert float(sw) == base + (1 - gr), (r, float(sw))
        # root-only gather on the sub-communicator
        g = m4t.gather(jnp.float32(r), root=0, comm=comm)
        if gr == 0:
            assert np.allclose(np.asarray(g), [base, base + 1]), (r, g)
        # alltoall within the group: member gr's block j = member j's block gr
        a2a = m4t.alltoall(jnp.asarray([10.0 * r, 10.0 * r + 1]), comm=comm)
        assert np.allclose(np.asarray(a2a),
                           [10.0 * base + gr, 10.0 * (base + 1) + gr]), (r, a2a)
        # root-only reduce: group root gets the sum, others their input
        red = m4t.reduce(jnp.float32(r), m4t.SUM, 1, comm=comm)
        assert float(red) == (2 * base + 1 if gr == 1 else r), (r, float(red))
        # scatter from group root 0: root passes (2,), others a template
        if gr == 0:
            sc = m4t.scatter(jnp.asarray([100.0 + r, 200.0 + r]), 0, comm=comm)
        else:
            sc = m4t.scatter(jnp.float32(0), 0, comm=comm)
        assert float(sc) == (100.0 if gr == 0 else 200.0) + base, (r, float(sc))
        m4t.barrier(comm=comm)
        print(f"SPLIT_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(4):
        assert f"SPLIT_OK{r}" in res.stdout


@needs_native
def test_sendrecv_any_source_large_symmetric():
    # Symmetric > 256 KiB (channel entry) exchange with ANY_SOURCE on
    # both sides: the native layer must progress the send while polling
    # for a source (draining the send first would deadlock both peers).
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        big = jnp.arange(200_000, dtype=jnp.float32) + r  # ~800 KB
        st = m4t.Status()
        got = m4t.sendrecv(big, jnp.zeros_like(big),
                           source=m4t.ANY_SOURCE, dest=1 - r, status=st)
        assert float(got[0]) == float(1 - r)
        assert st.source == 1 - r and st.Get_count(np.float32) == 200_000
        print(f"ANYSRC_BIG_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "ANYSRC_BIG_OK0" in res.stdout and "ANYSRC_BIG_OK1" in res.stdout


@needs_native
def test_split_status_comm_rank_and_proc_null():
    # Status on a Split comm reports the *communicator* rank (MPI
    # semantics), and a PROC_NULL receive resets a reused Status.
    res = launch(
        4,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        comm = m4t.Comm().Split([0, 0, 1, 1])  # {0,1}, {2,3}
        gr = r % 2
        st = m4t.Status()
        # group ring exchange: each member sends to the other
        got = m4t.sendrecv(jnp.float32(r), jnp.float32(0),
                           source=[1, 0], dest=[1, 0], comm=comm, status=st)
        assert st.source == 1 - gr, (r, st.source)  # comm rank, not global
        # PROC_NULL recv resets the status
        got2 = m4t.recv(jnp.float32(5), m4t.PROC_NULL, comm=comm, status=st)
        assert st.source == m4t.PROC_NULL and st.Get_count() == 0, st
        assert float(got2) == 5.0
        print(f"SPLITSTAT_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(4):
        assert f"SPLITSTAT_OK{r}" in res.stdout


@needs_native
def test_rank_divergent_send_recv_jitted():
    # Regression for the wire-threading bug: inside one jitted program,
    # XLA's CPU pipeline may delete optimization_barrier ties and
    # reorder independent side-effecting custom calls — without the
    # operand wire a rank's recv was scheduled before its own send and
    # both ranks deadlocked. (The eager variant above never sees this.)
    res = launch(
        2,
        """
        import jax, numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()

        def prog(x):
            if r == 1:
                m4t.send(x, dest=0, tag=5)
                return m4t.recv(jnp.zeros(()), source=0, tag=6)
            got = m4t.recv(jnp.zeros(()), source=1, tag=5)
            m4t.send(got + 10.0, dest=1, tag=6)
            return got

        out = jax.jit(prog)(jnp.float32(r))
        assert float(out) == (11.0 if r == 1 else 1.0), float(out)
        print(f"JITP2P_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "JITP2P_OK0" in res.stdout and "JITP2P_OK1" in res.stdout


@needs_native
def test_barrier_ordering_interleaved_writes(tmp_path):
    # The reference proves barrier ordering by interleaving writes from
    # all ranks into one file with sleeps and asserting every "start"
    # line precedes every "done" line (test_barrier.py:17-57).
    logf = os.path.join(tmp_path, "barrier_log.txt")
    res = launch(
        3,
        f"""
        import time, random
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        random.seed(r)
        time.sleep(random.uniform(0, 0.3))
        with open({logf!r}, "a") as f:
            f.write(f"start {{r}}\\n"); f.flush()
        m4t.barrier()
        time.sleep(random.uniform(0, 0.1))
        with open({logf!r}, "a") as f:
            f.write(f"done {{r}}\\n"); f.flush()
        """,
    )
    assert res.returncode == 0, res.stderr
    lines = open(logf).read().splitlines()
    starts = [i for i, l in enumerate(lines) if l.startswith("start")]
    dones = [i for i, l in enumerate(lines) if l.startswith("done")]
    assert len(starts) == 3 and len(dones) == 3, lines
    assert max(starts) < min(dones), lines


@needs_native
def test_stalled_peer_spin_timeout_aborts():
    # The stalled-peer failure path (reference: every MPI error ->
    # MPI_Abort, mpi_ops_common.h:60-78; here: spin timeout -> fatal ->
    # abort flag -> world teardown). A short M4T_SHM_SPIN_TIMEOUT_US
    # makes it testable: rank 1 never reaches the barrier.
    res = launch(
        2,
        """
        import time
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        if shm.rank() == 1:
            time.sleep(60)  # never participates
        m4t.barrier()
        """,
        env_extra={"M4T_SHM_SPIN_TIMEOUT_US": "2000000"},  # 2 s
        timeout=60,
    )
    assert res.returncode != 0
    assert "barrier timeout" in res.stderr, res.stderr
    assert "terminating world" in res.stderr


@needs_native
def test_stale_segment_not_joined():
    """A leftover segment (crashed or differently-sized previous
    world) must never be silently joined: attachers validate the
    header magic + world size (not just byte count), and a creator
    always unlinks and recreates fresh (ADVICE r4: a stale segment
    whose st_size passed the old check carried stale barrier and
    channel state into the new world)."""
    import struct
    import uuid

    name = f"/m4t_stale_{uuid.uuid4().hex[:8]}"
    seg_path = f"/dev/shm{name}"
    if not os.path.isdir("/dev/shm"):
        pytest.skip("no /dev/shm on this host")
    # plant a stale segment: valid magic, wrong world size, and a byte
    # count large enough to pass the attacher's st_size pre-check for
    # any world this test creates (sparse truncate: segment_bytes(2)
    # is ~2x coll chunks + 4 channels, well under 64 MiB)
    with open(seg_path, "wb") as f:
        f.truncate(64 << 20)
        f.seek(0)
        f.write(struct.pack("<II", 0x4D34544A, 999))
    script = f"""
    import struct, sys
    from mpi4jax_tpu.runtime.shm import _load_ext
    ext = _load_ext()
    try:
        ext.init({name!r}, 1, 2, 0)  # attach: must refuse the stale world
    except RuntimeError as e:
        assert "(code -2)" in str(e), str(e)
        print("ATTACH_REFUSED")
    else:
        sys.exit("attacher joined a stale segment")
    ext.init({name!r}, 0, 1, 1)  # create: must recreate fresh
    with open({seg_path!r}, "rb") as f:
        magic, ws = struct.unpack("<II", f.read(8))
    assert magic == 0x4D34544A and ws == 1, (hex(magic), ws)
    print("CREATED_FRESH")
    """
    path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"m4t_stale_{os.getpid()}.py"
    )
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    try:
        res = subprocess.run(
            [sys.executable, path], env=env, capture_output=True,
            text=True, timeout=120, cwd=REPO,
        )
        assert res.returncode == 0, res.stderr
        assert "ATTACH_REFUSED" in res.stdout
        assert "CREATED_FRESH" in res.stdout
    finally:
        for p in (path, seg_path):
            try:
                os.unlink(p)
            except OSError:
                pass


@needs_native
def test_32_rank_world():
    # The shm segment is runtime-sized from the launcher's -n (the
    # reference's mpirun has no compile-time world bound; the old
    # kMaxRanks=16 hard cap was round 3's one remaining wall): a
    # 32-rank world — twice the former cap — runs collectives and p2p
    # correctly. The world busy-spins, so on small CI hosts a 32-way
    # oversubscription can blow through the spin deadlines and flake:
    # drop to 16 ranks (still past the old cap) when the host has
    # fewer than ranks/2 cores.
    n_ranks = 32 if (os.cpu_count() or 1) >= 16 else 16
    res = launch(
        n_ranks,
        f"""
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        assert n == {n_ranks}
        s = m4t.allreduce(jnp.float32(r), op=m4t.SUM)
        assert float(s) == sum(range(n)), float(s)
        ag = m4t.allgather(jnp.float32(r))
        assert np.allclose(np.asarray(ag), np.arange(float(n)))
        sw = m4t.sendrecv(jnp.float32(r), jnp.float32(0),
                          source=(r - 1) % n, dest=(r + 1) % n)
        assert float(sw) == (r - 1) % n
        m4t.barrier()
        print(f"MAX_OK{{r}}.")
        """,
        timeout=480,
    )
    assert res.returncode == 0, res.stderr
    for r in range(n_ranks):
        # trailing delimiter: "MAX_OK1" must not match "MAX_OK10"
        assert f"MAX_OK{r}." in res.stdout


def test_launcher_rejects_oversized_world():
    import subprocess
    import sys

    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "65", "x.py"],
        capture_output=True, text=True, timeout=30, cwd=REPO,
    )
    assert res.returncode != 0
    assert "64" in res.stderr


@needs_native
def test_wildcard_skips_reserved_group_tags():
    # A recv(ANY_SOURCE, ANY_TAG) concurrent with a Split-comm group
    # collective must not claim the group's reserved-tag chunks
    # (shmcc.cpp kTagBase exclusion): rank 1 publishes its group gather
    # chunk to leader rank 0 well before rank 2's user message arrives,
    # so without the exclusion rank 0's wildcard recv would steal it
    # (wrong data or a fatal size/tag mismatch aborting the world).
    res = launch(
        4,
        """
        import time
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        sub = m4t.Comm().Split([0, 0, 1, 1])  # groups {0,1} and {2,3}
        if r == 0:
            # group-A leader: rank 1's reserved-tag gather chunk lands
            # on channel[1][0] well before rank 2's user message; the
            # wildcard recv must wait for the user message regardless
            st = m4t.Status()
            got = m4t.recv(jnp.zeros(5), m4t.ANY_SOURCE, status=st)
            assert st.Get_source() == 2, st
            assert st.Get_tag() == 9, st
            assert np.allclose(got, 2.0), got
            s = m4t.allreduce(jnp.float32(r), op=m4t.SUM, comm=sub)
            assert float(s) == 1.0, float(s)
        elif r == 1:
            # publishes the reserved-tag gather chunk to rank 0
            # immediately, long before rank 2's user send below
            s = m4t.allreduce(jnp.float32(r), op=m4t.SUM, comm=sub)
            assert float(s) == 1.0, float(s)
        else:
            if r == 2:
                time.sleep(0.5)
                m4t.send(jnp.full(5, 2.0), dest=0, tag=9)
            s = m4t.allreduce(jnp.float32(r), op=m4t.SUM, comm=sub)
            assert float(s) == 5.0, float(s)
        m4t.barrier()
        print(f"WILDCARD_GROUP_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    for r in range(4):
        assert f"WILDCARD_GROUP_OK{r}" in res.stdout


@needs_native
def test_reserved_and_foreign_sentinel_rejected_on_shm():
    # User tags in the reserved namespace and foreign negative
    # sentinels (mpi4py's implementation-dependent -2) must fail
    # loudly instead of silently corrupting or no-opping.
    res = launch(
        2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        try:
            m4t.send(jnp.ones(2), dest=1 - r, tag=1 << 20)
            raise SystemExit("reserved tag accepted")
        except ValueError as e:
            assert "reserved" in str(e), e
        try:
            m4t.recv(jnp.ones(2), source=-2)
            raise SystemExit("foreign sentinel accepted")
        except ValueError as e:
            assert "PROC_NULL" in str(e), e
        m4t.barrier()
        print(f"REJECT_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert "REJECT_OK0" in res.stdout and "REJECT_OK1" in res.stdout


@needs_native
def test_eager_fast_path_preserves_submission_order():
    # The eager fast path (token.py ordered_call: no ties outside a
    # trace) rests on XLA executing eager dispatches in submission
    # order per device. Two consecutive tagged sends against two
    # tag-matched recvs pin it: shm channels deliver in order, so any
    # reorder on either side is a loud tag-mismatch fatal, and a
    # cross-pairing send/recv order is a deadlock caught by the spin
    # timeout.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        if r == 0:
            m4t.send(jnp.full(3, 1.0), dest=1, tag=11)
            m4t.send(jnp.full(3, 2.0), dest=1, tag=22)
            got = m4t.recv(jnp.zeros(3), source=1, tag=33)
            assert np.allclose(got, 3.0)
        else:
            a = m4t.recv(jnp.zeros(3), source=0, tag=11)
            b = m4t.recv(jnp.zeros(3), source=0, tag=22)
            m4t.send(jnp.full(3, 3.0), dest=0, tag=33)
            assert np.allclose(a, 1.0) and np.allclose(b, 2.0)
        print(f"EAGER_ORDER_OK{r}")
        """,
        env_extra={"M4T_SHM_SPIN_TIMEOUT_US": "20000000"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert "EAGER_ORDER_OK0" in res.stdout and "EAGER_ORDER_OK1" in res.stdout


@needs_native
def test_shallow_water_on_launcher_world():
    # Decomposition invariance in the reference's own execution model:
    # a 2-rank launcher world solves the same problem as a single-rank
    # run of the same model, halos exchanged over the native shm
    # backend, and the gathered field must match the 1-rank solution.
    res = launch(
        2,
        """
        import numpy as np, jax, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        from mpi4jax_tpu.models.shallow_water import (
            ModelState, ShallowWaterConfig, ShallowWaterModel,
        )
        r = shm.rank()

        # 2-rank decomposed solve (halo sendrecvs ride the shm backend)
        cfg2 = ShallowWaterConfig(nx=72, ny=36, dims=(2, 1))
        model2 = ShallowWaterModel(cfg2)
        blocks = model2.initial_state_blocks()
        state = ModelState(*(jnp.asarray(b[r]) for b in blocks))
        state = jax.jit(lambda s: model2.step(s, first_step=True))(state)
        state = jax.jit(lambda s: model2.multistep(s, 20))(state)
        h2 = m4t.gather(state.h, 0)

        # single-rank reference solve of the identical problem,
        # computed redundantly on every rank (reference oracle style)
        cfg1 = ShallowWaterConfig(nx=72, ny=36, dims=(1, 1))
        model1 = ShallowWaterModel(cfg1)
        s1 = ModelState(*(jnp.asarray(b[0]) for b in model1.initial_state_blocks()))
        s1 = model1.step(s1, first_step=True)
        s1 = model1.multistep(s1, 20)

        if r == 0:
            whole = model2.reassemble(np.asarray(h2), (2, 1))
            ref = model1.reassemble(np.asarray(s1.h)[None], (1, 1))
            np.testing.assert_allclose(whole, ref, rtol=1e-5, atol=1e-6)
        m4t.barrier()
        print(f"SW_SHM_OK{r}")
        """,
        timeout=240,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert "SW_SHM_OK0" in res.stdout and "SW_SHM_OK1" in res.stdout


@needs_native
def test_unequal_split_on_launcher_world():
    # MPI_Comm_split parity: unequal-size groups are legal on the shm
    # backend (p2p-composed group collectives need no uniformity) —
    # only the XLA path requires equal replica_groups.
    res = launch(
        3,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        sub = m4t.Comm().Split([0, 0, 1])  # {0,1} and {2}
        s = m4t.allreduce(jnp.float32(r + 1), op=m4t.SUM, comm=sub)
        assert float(s) == (3.0 if r < 2 else 3.0), float(s)  # 1+2 | 3
        ag = m4t.allgather(jnp.float32(r), comm=sub)
        if r < 2:
            assert np.allclose(np.asarray(ag), [0.0, 1.0]), ag
        else:
            assert np.allclose(np.asarray(ag), [2.0]), ag
        sc = m4t.scan(jnp.float32(r + 1), op=m4t.SUM, comm=sub)
        assert float(sc) == [1.0, 3.0, 3.0][r], float(sc)
        m4t.barrier()
        print(f"UNEQ_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    for r in range(3):
        assert f"UNEQ_OK{r}" in res.stdout
