"""Native shared-memory backend tests, run through the real launcher
in subprocesses — the reference's crash-path/subprocess technique
(``tests/collective_ops/test_common.py:13-57`` run_in_subprocess) plus
its ``mpirun -np N pytest`` execution model, with
``python -m mpi4jax_tpu.launch`` in mpirun's role."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def launch(n, script, env_extra=None, timeout=120):
    path = os.path.join(os.environ.get("TMPDIR", "/tmp"), f"m4t_case_{os.getpid()}.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children don't need the 8-device trick
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n), path],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


@needs_native
def test_world_collectives():
    res = launch(
        4,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r, n = shm.rank(), shm.size()
        x = jnp.arange(4.0) + r
        assert np.allclose(m4t.allreduce(x, op=m4t.SUM),
                           np.arange(4.0) * n + sum(range(n)))
        assert np.allclose(m4t.allgather(jnp.float32(r)), np.arange(n))
        assert float(m4t.scan(jnp.float32(r), op=m4t.SUM)) == sum(range(r + 1))
        m4t.barrier()
        print(f"OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    for r in range(4):
        assert f"OK{r}" in res.stdout


@needs_native
def test_rank_divergent_send_recv():
    # The reference's deadlock-ordering pattern
    # (test_send_and_recv.py:91-110): asymmetric send/recv order across
    # ranks — expressible here because the shm backend is
    # multi-controller like the reference.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        x = jnp.full(3, float(r))
        if r == 0:
            m4t.send(x, dest=1, tag=1)
            got = m4t.recv(jnp.zeros(3), source=1, tag=2)
            assert np.allclose(got, 1.0)
        else:
            got = m4t.recv(jnp.zeros(3), source=0, tag=1)
            m4t.send(x, dest=0, tag=2)
            assert np.allclose(got, 0.0)
        print(f"P2P_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "P2P_OK0" in res.stdout and "P2P_OK1" in res.stdout


@needs_native
def test_large_message_chunking():
    # > 4 MiB collective slot and > 256 KiB p2p entry force the chunked
    # protocols.
    res = launch(
        2,
        """
        import numpy as np, jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        big = jnp.arange(3_000_000, dtype=jnp.float32) + r  # ~12 MB
        out = m4t.allreduce(big, op=m4t.SUM)
        assert np.allclose(out[:5], 2 * np.arange(5) + 1)
        partner = 1 - r
        sw = m4t.sendrecv(big, jnp.zeros_like(big), source=partner, dest=partner)
        assert float(sw[0]) == float(partner)
        print(f"BIG_OK{r}")
        """,
    )
    assert res.returncode == 0, res.stderr
    assert "BIG_OK0" in res.stdout and "BIG_OK1" in res.stdout


@needs_native
def test_abort_propagates():
    # Fail-fast parity (reference abort_on_error -> MPI_Abort,
    # tested via subprocess at test_common.py:60-88): one rank dying
    # must take the world down with a nonzero exit.
    res = launch(
        2,
        """
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        if shm.rank() == 1:
            raise SystemExit(7)
        import jax.numpy as jnp
        m4t.barrier()  # would hang forever without abort detection
        """,
        timeout=180,
    )
    assert res.returncode != 0
    assert "terminating world" in res.stderr


@needs_native
def test_debug_log_format():
    # Debug-log contract (reference test_common.py:118-146): rank
    # prefix, 8-char correlation id, op name, "done" with timing.
    res = launch(
        2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        m4t.allreduce(jnp.ones(4), op=m4t.SUM)
        """,
        env_extra={"MPI4JAX_TPU_DEBUG": "1"},
    )
    assert res.returncode == 0, res.stderr
    import re

    assert re.search(
        r"shmcc r[01] \| [a-z0-9]{8} \| Allreduce done \(\d\.\d{2}e[+-]\d+ s\)",
        res.stderr,
    ), res.stderr


@needs_native
def test_abi_info():
    from mpi4jax_tpu.runtime import shm

    info = shm.abi_info()
    assert info["max_ranks"] >= 2
    assert info["coll_chunk_bytes"] >= 1 << 20
