"""DP x TP MLP training equivalence (BASELINE.json config 5 analog).

Gold property: training over a (dp=2, tp=4) mesh — gradients synced
with allreduce, activations summed with allreduce, Megatron-f backward
sync — must match single-device training on the unsharded model
step-for-step."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi4jax_tpu.models import mlp

DP, TP = 2, 4
BATCH = 8  # per-dp-rank batch 4


@pytest.fixture(scope="module")
def mesh2d():
    devs = np.array(jax.devices()[: DP * TP]).reshape(DP, TP)
    return Mesh(devs, ("dp", "tp"))


def make_configs():
    dist = mlp.MLPConfig(
        in_dim=16, hidden_dim=32, out_dim=8, n_blocks=2, tp_size=TP
    )
    single = mlp.MLPConfig(
        in_dim=16, hidden_dim=32, out_dim=8, n_blocks=2,
        tp_size=1, tp_axis=None, dp_axis=None,
    )
    return dist, single


def shard_params(full_params, tp_rank):
    """Slice the full model's weights into tp_rank's blocks."""
    h_loc = full_params["blocks"][0][0].shape[1] // TP
    blocks = []
    for w_col, w_row, b in full_params["blocks"]:
        blocks.append(
            (
                w_col[:, tp_rank * h_loc : (tp_rank + 1) * h_loc],
                w_row[tp_rank * h_loc : (tp_rank + 1) * h_loc, :],
                b,
            )
        )
    return {"blocks": blocks, "head": full_params["head"]}


def test_dp_tp_training_matches_single_device(mesh2d):
    dist_cfg, single_cfg = make_configs()
    key = jax.random.PRNGKey(0)
    full_params = mlp.init_params(single_cfg, key)

    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(kx, (BATCH, single_cfg.in_dim), jnp.float32)
    labels = jax.random.randint(ky, (BATCH,), 0, single_cfg.out_dim)
    y = jax.nn.one_hot(labels, single_cfg.out_dim)

    # --- single device reference ---
    p_ref = full_params
    losses_ref = []
    for _ in range(3):
        p_ref, l = mlp.train_step(single_cfg, p_ref, (x, y))
        losses_ref.append(float(l))

    # --- distributed: stack per-(dp,tp) params and batch shards ---
    def stack_over_mesh(fn):
        """fn(dp_rank, tp_rank) -> pytree; stack into (DP, TP, ...)."""
        rows = [[fn(d, t) for t in range(TP)] for d in range(DP)]
        return jax.tree.map(
            lambda *leaves: jnp.stack(
                [jnp.stack(leaves[d * TP : (d + 1) * TP]) for d in range(DP)]
            ),
            *[rows[d][t] for d in range(DP) for t in range(TP)],
        )

    params_stacked = stack_over_mesh(lambda d, t: shard_params(full_params, t))
    bsz = BATCH // DP
    batch_stacked = stack_over_mesh(
        lambda d, t: (x[d * bsz : (d + 1) * bsz], y[d * bsz : (d + 1) * bsz])
    )

    def step_body(params, batch):
        params = jax.tree.map(lambda a: a.reshape(a.shape[2:]), params)
        batch = jax.tree.map(lambda a: a.reshape(a.shape[2:]), batch)
        new_params, loss = mlp.train_step(dist_cfg, params, batch, n_dp=DP)
        pad = lambda a: a.reshape((1, 1) + a.shape)
        return jax.tree.map(pad, new_params), pad(loss * jnp.ones(()))

    step = jax.jit(
        shard_map(
            step_body,
            mesh=mesh2d,
            in_specs=(P("dp", "tp"), P("dp", "tp")),
            out_specs=(P("dp", "tp"), P("dp", "tp")),
            check_vma=False,
        )
    )

    p_dist = params_stacked
    losses_dist = []
    for _ in range(3):
        p_dist, l = step(p_dist, batch_stacked)
        l = np.asarray(l)
        # loss is dp-averaged and replicated everywhere
        np.testing.assert_allclose(l, l[0, 0], rtol=1e-5)
        losses_dist.append(float(l[0, 0]))

    np.testing.assert_allclose(losses_dist, losses_ref, rtol=1e-4)

    # final params: tp shards must reassemble to the reference weights
    p_dist_np = jax.tree.map(np.asarray, p_dist)
    for i, (w_col_ref, w_row_ref, b_ref) in enumerate(p_ref["blocks"]):
        w_col = np.concatenate(
            [p_dist_np["blocks"][i][0][0, t] for t in range(TP)], axis=1
        )
        w_row = np.concatenate(
            [p_dist_np["blocks"][i][1][0, t] for t in range(TP)], axis=0
        )
        np.testing.assert_allclose(w_col, np.asarray(w_col_ref), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w_row, np.asarray(w_row_ref), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            p_dist_np["blocks"][i][2][0, 0], np.asarray(b_ref), rtol=1e-4, atol=1e-5
        )
    # dp replicas must agree
    np.testing.assert_allclose(
        p_dist_np["blocks"][0][0][0, 1], p_dist_np["blocks"][0][0][1, 1], rtol=1e-5
    )
