"""Cross-rank doctor, flight recorder, and Perfetto trace export
(``mpi4jax_tpu/observability/{doctor,recorder,trace}.py``).

Covers the ISSUE-2 acceptance surface:

- doctor verdicts on synthetic per-rank logs: clean, mismatch at seq
  k (naming seq, fingerprints, ranks), straggler, hung-vs-dead-vs-
  behind, one-rank-missing;
- flight recorder ring semantics + JSONL dump format;
- Chrome trace-event export: structural schema checks plus a golden
  file pinning the exact output for a fixed input;
- the CLI (``python -m mpi4jax_tpu.observability.doctor``) smoke +
  exit-code contract;
- end-to-end: a real CPU 2-rank ``mpi4jax_tpu.launch --events-dir``
  round trip (clean -> no findings; injected collective mismatch ->
  the launcher's own diagnosis names the diverging seq and ranks).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from mpi4jax_tpu.observability import doctor, trace
from mpi4jax_tpu.observability.recorder import (
    DUMP_NAME,
    FlightRecorder,
    fingerprint,
)

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "trace_golden.json")


# ---------------------------------------------------------------------
# synthetic log builders
# ---------------------------------------------------------------------


def emission(rank, seq, op, shape, t, dtype="float32", axes=("ranks",),
             world=2, nbytes=16):
    return {
        "kind": "emission", "rank": rank, "seq": seq, "op": op,
        "shape": shape, "dtype": dtype, "axes": list(axes),
        "world": world, "bytes": nbytes, "cid": f"c{rank:02d}{seq:04d}",
        "t": t,
    }


def heartbeat(rank, t):
    return {"kind": "heartbeat", "rank": rank, "source": "hb", "t": t}


def latency(rank, op, seconds, t, seq=None):
    return {"kind": "latency", "rank": rank, "op": op,
            "seconds": seconds, "t": t, "seq": seq}


def write_logs(tmp_path, per_rank):
    for rank, records in per_rank.items():
        path = tmp_path / f"events-rank{rank}.jsonl"
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return str(tmp_path)


def clean_world(n_ranks=2, n_seq=4):
    """Every rank emits the identical collective stream."""
    return {
        r: [
            emission(r, s, "AllReduce", [8], 100.0 + s)
            for s in range(1, n_seq + 1)
        ]
        for r in range(n_ranks)
    }


# ---------------------------------------------------------------------
# doctor verdicts on synthetic logs
# ---------------------------------------------------------------------


def test_clean_run_no_findings(tmp_path):
    d = write_logs(tmp_path, clean_world())
    report = doctor.diagnose([d])
    assert report["ranks"] == [0, 1]
    assert report["seqs"] == {"0": 4, "1": 4}
    assert report["findings"] == []
    assert "no findings" in doctor.format_report(report)


def test_mismatch_names_seq_fingerprints_and_ranks(tmp_path):
    logs = clean_world(n_ranks=3)
    # rank 2 diverges at seq 3: AllGather instead of AllReduce
    logs[2][2] = emission(2, 3, "AllGather", [8], 103.0)
    d = write_logs(tmp_path, logs)
    report = doctor.diagnose([d])
    kinds = [f["kind"] for f in report["findings"]]
    assert "mismatch" in kinds
    m = report["findings"][kinds.index("mismatch")]
    assert m["seq"] == 3
    assert m["fingerprints"]["0"] == "AllReduce[8:float32]@ranks"
    assert m["fingerprints"]["2"] == "AllGather[8:float32]@ranks"
    groups = {g["fingerprint"]: g["ranks"] for g in m["groups"]}
    assert groups["AllReduce[8:float32]@ranks"] == [0, 1]
    assert groups["AllGather[8:float32]@ranks"] == [2]
    text = doctor.format_report(report)
    assert "MISMATCH at seq 3" in text
    assert "AllGather[8:float32]@ranks" in text


def test_shape_and_dtype_divergence_is_a_mismatch(tmp_path):
    logs = clean_world()
    logs[1][1] = emission(1, 2, "AllReduce", [4], 102.0)  # shape fork
    d = write_logs(tmp_path, logs)
    (m,) = [f for f in doctor.diagnose([d])["findings"]
            if f["kind"] == "mismatch"]
    assert m["seq"] == 2
    assert m["fingerprints"]["1"] == "AllReduce[4:float32]@ranks"


def test_hang_verdicts_hung_dead_and_behind(tmp_path):
    logs = clean_world(n_ranks=4, n_seq=5)
    # rank 1 stops at seq 2 but keeps heartbeating long after: hung
    logs[1] = logs[1][:2] + [heartbeat(1, 130.0)]
    # rank 2 stops at seq 2 and its heartbeats stop there too: dead
    logs[2] = logs[2][:2] + [heartbeat(2, 102.0)]
    # rank 3 stops at seq 2 with no heartbeat records at all: behind
    logs[3] = logs[3][:2]
    d = write_logs(tmp_path, logs)
    report = doctor.diagnose([d])
    verdicts = {f["rank"]: f for f in report["findings"]
                if f["kind"] == "hang"}
    assert verdicts[1]["verdict"] == "hung"
    assert verdicts[2]["verdict"] == "dead"
    assert verdicts[3]["verdict"] == "behind"
    for f in verdicts.values():
        assert f["last_seq"] == 2 and f["front_seq"] == 5 and f["gap"] == 3
        assert f["front_ranks"] == [0]
        # what the stuck ranks never reached
        assert f["stuck_before"] == "AllReduce[8:float32]@ranks"
    text = doctor.format_report(report)
    assert "HANG (alive but stuck): rank 1" in text
    assert "RANK DIED: rank 2" in text
    assert "RANK BEHIND" in text


def test_hang_gap_threshold(tmp_path):
    logs = clean_world(n_seq=4)
    logs[1] = logs[1][:3]  # one seq behind
    d = write_logs(tmp_path, logs)
    assert doctor.diagnose([d], hang_gap=2)["findings"] == []
    behind = doctor.diagnose([d], hang_gap=1)["findings"]
    assert [f["kind"] for f in behind] == ["hang"]


def test_missing_rank_detected_from_world_size(tmp_path):
    logs = clean_world(n_ranks=2)  # records say world=2...
    del logs[1]  # ...but rank 1 produced no log at all
    d = write_logs(tmp_path, logs)
    (f,) = doctor.diagnose([d])["findings"]
    assert f["kind"] == "missing_rank" and f["rank"] == 1 and f["world"] == 2
    assert "MISSING RANK" in doctor.format_report(doctor.diagnose([d]))


def test_straggler_flagged_against_peer_median(tmp_path):
    logs = clean_world(n_ranks=4)
    for r in range(4):
        per = 0.05 if r == 3 else 0.001  # rank 3 is 50x slower
        for i in range(5):
            logs[r].append(latency(r, "AllReduce", per, 105.0 + i))
    d = write_logs(tmp_path, logs)
    (f,) = [x for x in doctor.diagnose([d])["findings"]
            if x["kind"] == "straggler"]
    assert f["rank"] == 3 and f["op"] == "AllReduce"
    assert f["ratio"] == pytest.approx(50.0, rel=0.01)
    assert "STRAGGLER: rank 3" in doctor.format_report(doctor.diagnose([d]))


def test_straggler_needs_enough_samples(tmp_path):
    logs = clean_world()
    logs[1].append(latency(1, "AllReduce", 10.0, 105.0))  # 1 sample only
    logs[0].extend(latency(0, "AllReduce", 0.001, 105.0 + i)
                   for i in range(5))
    d = write_logs(tmp_path, logs)
    assert doctor.diagnose([d])["findings"] == []


def test_straggler_min_samples_default_and_tunable(tmp_path):
    """4 slow samples are below the default floor of 5 (1-sample or
    few-sample noise must not brand a straggler), but the floor is
    flag-tunable down when a short run is all the evidence there is."""
    logs = clean_world()
    logs[0].extend(latency(0, "AllReduce", 0.001, 105.0 + i)
                   for i in range(6))
    logs[1].extend(latency(1, "AllReduce", 0.05, 105.0 + i)
                   for i in range(4))  # 50x slower, but only 4 samples
    d = write_logs(tmp_path, logs)
    assert doctor.diagnose([d])["findings"] == []
    (f,) = doctor.diagnose([d], straggler_min_samples=3)["findings"]
    assert f["kind"] == "straggler" and f["rank"] == 1
    # the payload names its statistical footing
    assert f["samples"] == 4 and f["min_samples"] == 3
    assert f["peer_samples"] == {"0": 6}


def test_straggler_finding_reports_sample_counts(tmp_path):
    logs = clean_world(n_ranks=3)
    for r in range(3):
        per = 0.08 if r == 2 else 0.002
        for i in range(5 + r):
            logs[r].append(latency(r, "AllReduce", per, 105.0 + i))
    d = write_logs(tmp_path, logs)
    (f,) = [x for x in doctor.diagnose([d])["findings"]
            if x["kind"] == "straggler"]
    assert f["rank"] == 2 and f["samples"] == 7
    assert f["peer_samples"] == {"0": 5, "1": 6}
    assert f["min_samples"] == doctor.DEFAULT_STRAGGLER_MIN_SAMPLES


def test_rank_from_filename_fallback(tmp_path):
    # records without a rank field are attributed via the filename
    for rank in (0, 1):
        with open(tmp_path / f"old-rank{rank}.jsonl", "w") as f:
            rec = emission(rank, 1, "AllReduce", [8], 100.0)
            del rec["rank"]
            f.write(json.dumps(rec) + "\n")
    by_rank = doctor.load([str(tmp_path)])
    assert sorted(by_rank) == [0, 1]


def test_pre_seq_logs_align_positionally(tmp_path):
    # artifacts from before seq stamping: file order becomes the seq
    logs = clean_world()
    for recs in logs.values():
        for rec in recs:
            del rec["seq"]
    logs[1][-1]["op"] = "Bcast"
    d = write_logs(tmp_path, logs)
    (m,) = [f for f in doctor.diagnose([d])["findings"]
            if f["kind"] == "mismatch"]
    assert m["seq"] == 4


def test_recorder_dump_and_events_sink_merge(tmp_path):
    """A rank represented only by its flight-recorder dump (its event
    sink never flushed) still participates in alignment."""
    logs = clean_world()
    rank1 = logs.pop(1)
    d = write_logs(tmp_path, logs)
    with open(tmp_path / "recorder-rank1.jsonl", "w") as f:
        f.write(json.dumps({"kind": "recorder_meta", "rank": 1,
                            "reason": "signal:SIGTERM", "last_seq": 3}) + "\n")
        for rec in rank1[:3]:  # one emission short of rank 0
            rec = dict(rec, kind="recorder")
            f.write(json.dumps(rec) + "\n")
    report = doctor.diagnose([d])
    (f_,) = [f for f in report["findings"] if f["kind"] == "hang"]
    assert f_["rank"] == 1 and f_["last_seq"] == 3


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------


def test_recorder_ring_bounded_and_monotonic():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        seq = fr.record("AllReduce", cid=f"c{i}", nbytes=4,
                        dtype="float32", shape=(2,), axes=("ranks",), world=2)
        assert seq == i + 1
    snap = fr.snapshot()
    assert len(snap) == 4  # bounded
    assert [r["seq"] for r in snap] == [7, 8, 9, 10]
    assert fr.seq == 10
    fr.reset()
    assert fr.snapshot() == [] and fr.seq == 0


def test_recorder_disabled_records_nothing():
    fr = FlightRecorder(capacity=4)
    fr.enable(False)
    assert fr.record("AllReduce", cid="x") == 0
    assert fr.snapshot() == []


def test_recorder_dump_schema(tmp_path, monkeypatch):
    monkeypatch.setenv("M4T_RANK", "3")
    fr = FlightRecorder(capacity=8)
    fr.record("AllReduce", cid="aaaa", nbytes=32, dtype="float32",
              shape=(4, 2), axes=("dp",), world=8)
    path = str(tmp_path / DUMP_NAME.format(rank=3))
    assert fr.dump(path, reason="test") == path
    lines = [json.loads(ln) for ln in open(path)]
    meta, rec = lines
    assert meta["kind"] == "recorder_meta"
    assert meta["rank"] == 3 and meta["reason"] == "test"
    assert meta["last_seq"] == 1 and meta["entries"] == 1
    assert rec["kind"] == "recorder" and rec["rank"] == 3
    assert rec["seq"] == 1 and rec["op"] == "AllReduce"
    assert rec["shape"] == [4, 2] and rec["axes"] == ["dp"]
    assert fingerprint(rec) == "AllReduce[4x2:float32]@dp"


def test_recorder_fed_by_op_emissions():
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.observability import flight_recorder

    flight_recorder.reset()
    base = flight_recorder.seq
    m4t.allreduce(jnp.ones((4, 2)))
    m4t.allgather(jnp.ones(3, jnp.int8))
    snap = flight_recorder.snapshot()[-2:]
    assert [r["op"] for r in snap] == ["AllReduce", "AllGather"]
    assert [r["seq"] for r in snap] == [base + 1, base + 2]
    assert snap[0]["shape"] == [4, 2] and snap[0]["bytes"] == 32
    assert snap[1]["dtype"] == "int8"


def test_fingerprint_edge_cases():
    assert fingerprint({"op": "Barrier", "shape": []}) == (
        "Barrier[scalar:?]@<none>"
    )
    assert fingerprint({"op": "Send", "bytes": 64, "dtype": "int8",
                        "axes": ["x", "y"]}) == "Send[64B:int8]@x,y"


# ---------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------


def synthetic_trace_world():
    """Fixed input for the golden/schema tests (all timestamps
    pinned; regenerate the golden with
    ``python -m tests.test_doctor`` after intentional changes)."""
    return {
        0: [
            emission(0, 1, "AllReduce", [8], 100.0),
            emission(0, 2, "AllGather", [8], 101.0, nbytes=32),
            latency(0, "AllReduce", 0.002, 100.5, seq=1),
            heartbeat(0, 101.5),
        ],
        1: [
            emission(1, 1, "AllReduce", [8], 100.25),
            emission(1, 2, "AllGather", [8], 101.25, nbytes=32),
            latency(1, "AllReduce", 0.004, 100.75, seq=1),
        ],
    }


def test_trace_schema_is_valid_chrome_trace(tmp_path):
    obj = trace.build_trace(synthetic_trace_world())
    assert isinstance(obj["traceEvents"], list) and obj["traceEvents"]
    assert obj["displayTimeUnit"] == "ms"
    phases = set()
    for ev in obj["traceEvents"]:
        # every event carries the required Chrome trace-event keys
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("M", "i", "X", "C")
        assert isinstance(ev["pid"], int)
        phases.add(ev["ph"])
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] > 0
        if ev["ph"] == "i":
            assert ev["s"] == "t"
    # all four families present: metadata, instants, slices, counters
    assert phases == {"M", "i", "X", "C"}
    # one process track per rank, named
    names = {
        (ev["pid"], ev["args"]["name"])
        for ev in obj["traceEvents"]
        if ev["name"] == "process_name"
    }
    assert names == {(0, "rank 0"), (1, "rank 1")}
    # duration slice reconstructed as (end - seconds, seconds)
    (slice0,) = [ev for ev in obj["traceEvents"]
                 if ev["ph"] == "X" and ev["pid"] == 0]
    assert slice0["dur"] == pytest.approx(2000.0)  # 2 ms in micros
    # counters accumulate payload bytes
    counters = [ev["args"]["cumulative"] for ev in obj["traceEvents"]
                if ev["ph"] == "C" and ev["pid"] == 0
                and ev["name"] == "payload bytes"]
    assert counters == [16, 48]
    # each latency sample that joins its emission (here: by seq) gets
    # an achieved-bandwidth counter from the cost model: 16B payload,
    # world 2 -> 16B on the wire, over 2ms on rank 0
    (ach0,) = [ev for ev in obj["traceEvents"]
               if ev["ph"] == "C" and ev["pid"] == 0
               and ev["name"] == "achieved GB/s"]
    assert ach0["args"]["gbps"] == pytest.approx(16 / 0.002 / 1e9)


def test_trace_golden_file():
    """The exact export for the fixed input is pinned by a golden
    file — any schema drift must be an intentional, reviewed change."""
    obj = trace.build_trace(synthetic_trace_world())
    normalized = json.loads(json.dumps(obj, sort_keys=True))
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert normalized == golden


def test_trace_export_loads_back_as_json(tmp_path):
    d = write_logs(tmp_path, clean_world())
    out = str(tmp_path / "trace.json")
    obj = trace.export([d], out)
    assert obj is not None
    with open(out) as f:
        loaded = json.load(f)
    assert loaded["traceEvents"]
    assert loaded["otherData"]["ranks"] == [0, 1]


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _run_cli(module, *argv):
    return subprocess.run(
        [sys.executable, "-m", module, *argv],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def test_doctor_cli_help_smoke():
    res = _run_cli("mpi4jax_tpu.observability.doctor", "--help")
    assert res.returncode == 0, res.stderr
    assert "mismatch" in res.stdout and "--hang-gap" in res.stdout


def test_doctor_cli_exit_codes_and_json(tmp_path):
    (tmp_path / "clean").mkdir()
    (tmp_path / "bad").mkdir()
    clean = write_logs(tmp_path / "clean", clean_world())
    res = _run_cli("mpi4jax_tpu.observability.doctor", clean)
    assert res.returncode == 0, res.stderr

    logs = clean_world()
    logs[1][2] = emission(1, 3, "Bcast", [8], 103.0)
    bad = write_logs(tmp_path / "bad", logs)
    out_trace = str(tmp_path / "t.json")
    res = _run_cli("mpi4jax_tpu.observability.doctor", bad,
                   "--json", "--trace", out_trace)
    assert res.returncode == 1
    report = json.loads(res.stdout)
    assert report["findings"][0]["kind"] == "mismatch"
    assert report["findings"][0]["seq"] == 3
    assert json.load(open(out_trace))["traceEvents"]

    empty = tmp_path / "empty"
    empty.mkdir()
    res = _run_cli("mpi4jax_tpu.observability.doctor", str(empty))
    assert res.returncode == 2


def test_doctor_json_schema_contract(tmp_path):
    """``--json`` is a stable machine contract (the resilience
    supervisor and CI parse it): top-level keys, the schema version
    tag, and the per-kind finding fields documented in doctor.py must
    not drift without a version bump."""
    logs = clean_world(n_ranks=3, n_seq=4)
    logs[1][2] = emission(1, 3, "Bcast", [8], 103.0)     # mismatch @3
    logs[2] = logs[2][:2] + [heartbeat(2, 120.0)]        # hung @2
    d = write_logs(tmp_path, logs)

    report = doctor.diagnose([d])
    assert report["schema"] == doctor.SCHEMA == "m4t-doctor/1"
    assert set(report) == {"schema", "ranks", "records", "seqs",
                           "findings"}
    kinds = {f["kind"] for f in report["findings"]}
    assert kinds == {"mismatch", "hang"}
    (m,) = [f for f in report["findings"] if f["kind"] == "mismatch"]
    assert {"kind", "seq", "fingerprints", "groups"} <= set(m)
    for g in m["groups"]:
        assert {"fingerprint", "ranks"} <= set(g)
    (h,) = [f for f in report["findings"] if f["kind"] == "hang"]
    assert {"kind", "rank", "verdict", "last_seq", "front_seq", "gap",
            "front_ranks", "stuck_before", "last_heartbeat_t",
            "last_emission_t"} <= set(h)
    assert h["verdict"] in ("hung", "dead", "behind")

    # the CLI emits the same contract, with the exit codes unchanged
    res = _run_cli("mpi4jax_tpu.observability.doctor", d, "--json")
    assert res.returncode == 1
    cli_report = json.loads(res.stdout)
    assert cli_report["schema"] == "m4t-doctor/1"
    assert cli_report["findings"] == json.loads(
        json.dumps(report["findings"], default=str)
    )

    # and the supervisor's classifier consumes it directly
    from mpi4jax_tpu.resilience import classify

    assert classify(report, 1)["klass"] == "deterministic"


def test_trace_cli_smoke(tmp_path):
    d = write_logs(tmp_path, clean_world())
    out = str(tmp_path / "trace.json")
    res = _run_cli("mpi4jax_tpu.observability.trace", d, "-o", out)
    assert res.returncode == 0, res.stderr
    assert json.load(open(out))["traceEvents"]


# ---------------------------------------------------------------------
# end-to-end: real 2-rank launcher worlds on CPU
# ---------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


def _launch(tmp_path, n, script, *launch_args, timeout=180):
    path = str(tmp_path / "case.py")
    with open(path, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(script))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", str(n),
         *launch_args, path],
        env=env, capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )


@needs_native
def test_launch_events_dir_clean_roundtrip(tmp_path):
    """The tier-1 smoke the ISSUE asks for: a clean 2-rank
    ``launch --events-dir`` run produces per-rank sinks + recorder
    dumps, and the doctor finds nothing wrong with them."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        x = jnp.arange(4.0) + shm.rank()
        for _ in range(3):
            x = m4t.allreduce(x)
        m4t.barrier()
        print(f"OK{shm.rank()}")
        """,
        "--events-dir", rundir,
    )
    assert res.returncode == 0, res.stderr
    assert "OK0" in res.stdout and "OK1" in res.stdout
    produced = sorted(os.listdir(rundir))
    assert "events-rank0.jsonl" in produced
    assert "events-rank1.jsonl" in produced
    assert "recorder-rank0.jsonl" in produced
    assert "recorder-rank1.jsonl" in produced
    report = doctor.diagnose([rundir])
    assert report["ranks"] == [0, 1]
    assert report["findings"] == []
    assert report["seqs"]["0"] == report["seqs"]["1"] == 4
    # the artifacts also make a loadable trace
    out = str(tmp_path / "trace.json")
    assert trace.export([rundir], out) is not None
    assert json.load(open(out))["otherData"]["ranks"] == [0, 1]


@needs_native
def test_launch_injected_mismatch_is_diagnosed(tmp_path):
    """Acceptance: a 2-rank run with an injected collective mismatch
    gets a diagnosis naming the diverging seq, fingerprints, ranks —
    from the launcher itself (``--doctor``; the watchdog covers the
    case where the mismatch deadlocks instead of completing)."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 2,
        """
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        x = jnp.arange(4.0) + r
        x = m4t.allreduce(x)
        x = m4t.allreduce(x)
        if r == 0:
            m4t.allreduce(x)   # seq 3 on rank 0...
        else:
            m4t.allgather(x)   # ...diverges on rank 1
        """,
        "--events-dir", rundir, "--doctor", "--hang-timeout", "60",
    )
    assert "MISMATCH at seq 3" in res.stderr, res.stderr
    assert "AllReduce[4:float32]" in res.stderr
    assert "AllGather[4:float32]" in res.stderr
    # the offline doctor agrees with the launcher's inline diagnosis
    (m,) = [f for f in doctor.diagnose([rundir])["findings"]
            if f["kind"] == "mismatch"]
    assert m["seq"] == 3
    assert m["groups"][0]["ranks"] == [0] or m["groups"][0]["ranks"] == [1]


@needs_native
def test_launch_hang_watchdog_diagnoses_stuck_rank(tmp_path):
    """A rank that never joins its peer's collective trips the hang
    watchdog; the diagnosis names the stuck rank and where it
    stopped. slow-marked: costs ~hang-timeout wall-clock."""
    rundir = str(tmp_path / "run")
    res = _launch(
        tmp_path, 2,
        """
        import time
        import jax.numpy as jnp
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        r = shm.rank()
        x = m4t.allreduce(jnp.arange(4.0) + r)
        if r == 0:
            m4t.barrier()      # rank 1 never joins: blocks
        else:
            time.sleep(120)    # alive (heartbeats) but silent
        """,
        "--events-dir", rundir, "--hang-timeout", "12", "--heartbeat", "1",
    )
    assert res.returncode == 124, (res.returncode, res.stderr)
    assert "hang watchdog fired" in res.stderr
    assert "rank 1" in res.stderr
    report = doctor.diagnose([rundir])
    hangs = [f for f in report["findings"] if f["kind"] == "hang"]
    assert hangs and hangs[0]["rank"] == 1
    assert hangs[0]["verdict"] in ("hung", "dead")


test_launch_hang_watchdog_diagnoses_stuck_rank = pytest.mark.slow(
    test_launch_hang_watchdog_diagnoses_stuck_rank
)


if __name__ == "__main__":
    # regenerate the golden trace file after an intentional schema change
    obj = trace.build_trace(synthetic_trace_world())
    with open(GOLDEN, "w") as f:
        json.dump(json.loads(json.dumps(obj, sort_keys=True)), f,
                  indent=1, sort_keys=True)
    print(f"golden rewritten: {GOLDEN}")
