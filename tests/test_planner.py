"""Planner subsystem, device-free half: plan schema + cache protocol,
cost-model impl variants, the autotune sweep (analytic seed, measured
refinement, lossy gating), the CLI, and the cross-layer plan-key /
fingerprint drift pins.

Regen the golden plan-cache pin after an intentional schema change::

    python tests/test_planner.py --regen
"""

import json
import os
import subprocess
import sys

import pytest

from mpi4jax_tpu.observability import costmodel
from mpi4jax_tpu.planner import autotune, plan as planmod

pytestmark = pytest.mark.tuning

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "plan_golden.json")

#: the fixed tune invocation the golden file pins: analytic seed over
#: a 3-bucket float32 grid at world 8, refined by a synthetic measured
#: table that makes the Pallas ring 10x faster than HLO
GOLDEN_KEYS = dict(platform="cpu", world=8, dtypes=("float32",),
                   buckets=(13, 21, 25))
GOLDEN_TABLE = {"schema": autotune.TABLE_SCHEMA,
                "gbps": {"pallas_ring": 100.0, "hlo": 10.0}}


def golden_plan():
    keys = autotune.default_keys(**GOLDEN_KEYS)
    planobj, _ = autotune.sweep(keys, measured=GOLDEN_TABLE,
                                gbps=25.0, alpha=1e-6)
    return planobj


# ---------------------------------------------------------------------
# plan keys and the cross-layer drift pin
# ---------------------------------------------------------------------


def test_plan_key_literal_pin():
    # the exact key string is a contract (cache files, bench records,
    # decision logs all carry it); changing it invalidates every
    # persisted plan, so it must not drift by accident
    key = planmod.plan_key(
        "AllReduce", nbytes=4096 * 4, dtype="float32", world=8,
        axes=("ranks",), platform="cpu",
    )
    assert key == "AllReduce|b15|float32|w8|ranks|cpu"
    assert planmod.plan_key(
        "AllGather", nbytes=0, dtype=None, world=None, axes=(),
        platform="tpu:v5e",
    ) == "AllGather|b0|?|w1|<none>|tpu:v5e"


def test_plan_key_bucket_roundtrip():
    for nbytes in (1, 2, 3, 1023, 1024, 1025, 1 << 20, (1 << 20) + 1):
        bucket = planmod.payload_bucket(nbytes)
        lo, hi = planmod.bucket_bounds(bucket)
        assert lo <= nbytes < hi, (nbytes, bucket, lo, hi)
    info = planmod.parse_key("AllReduce|b15|float32|w8|ranks|cpu")
    assert info == {"op": "AllReduce", "bucket": 15, "dtype": "float32",
                    "world": 8, "axes": ("ranks",), "platform": "cpu"}


def test_key_from_record_matches_all_telemetry_layers():
    """Satellite pin: the plan key computed from a runtime emission
    record, a recorder entry, a static CollectiveSite JSON, and the
    cost-model record shape are byte-identical — the planner joins all
    four layers by this key."""
    fields = dict(op="AllReduce", bytes=16384, dtype="float32",
                  axes=["ranks"], world=8)
    emission = dict(fields, kind="emission", cid="aaaaaaaa", seq=1)
    recorder_entry = dict(fields, kind="recorder", cid="aaaaaaaa", seq=1)
    site_json = dict(fields, index=0, prim="tpu_allreduce",
                     shape=[4096], source="x.py:1")
    keys = {
        planmod.key_from_record(rec, "cpu")
        for rec in (fields, emission, recorder_entry, site_json)
    }
    assert keys == {"AllReduce|b15|float32|w8|ranks|cpu"}, keys


def test_keys_from_records_folds_quantized_into_allreduce():
    records = [
        {"op": "QuantizedAllReduce", "bytes": 16384, "dtype": "float32",
         "axes": ["ranks"], "world": 8},
        {"op": "AllReduce", "bytes": 16384, "dtype": "float32",
         "axes": ["ranks"], "world": 8},
        {"op": "Barrier", "bytes": 0, "axes": ["ranks"], "world": 8},
    ]
    keys = planmod.keys_from_records(records, "cpu")
    # quantized measurements refine the AllReduce key; Barrier is not
    # plannable
    assert keys == ["AllReduce|b15|float32|w8|ranks|cpu"], keys


# ---------------------------------------------------------------------
# cost model impl variants (literal numbers)
# ---------------------------------------------------------------------


def test_cost_impl_pallas_ring_same_bytes_distinct_algorithm():
    base = costmodel.cost("AllReduce", nbytes=4096, world=8,
                          dtype="float32")
    ring = costmodel.cost("AllReduce", nbytes=4096, world=8,
                          dtype="float32", impl="pallas_ring")
    assert ring["wire_bytes"] == base["wire_bytes"] == 7168
    assert ring["steps"] == base["steps"] == 14
    assert ring["algorithm"] == "pallas RDMA ring RS+AG"
    assert ring["impl"] == "pallas_ring"
    assert "impl" not in base


def test_cost_impl_quantized_matches_quantized_op_model():
    as_impl = costmodel.cost("AllReduce", nbytes=4096, world=8,
                             dtype="float32", impl="quantized")
    as_op = costmodel.cost("QuantizedAllReduce", nbytes=4096, world=8,
                           dtype="float32")
    assert as_impl["wire_bytes"] == as_op["wire_bytes"] == 3640
    assert as_impl["steps"] == as_op["steps"]
    assert as_impl["op"] == "AllReduce"


def test_cost_impl_hierarchical_literal():
    c = costmodel.cost("AllReduce", nbytes=4096, world=8,
                       dtype="float32", impl="hierarchical",
                       params={"fast": 4})
    # fast ring RS+AG: 2*(3/4)*4096 = 6144; slow ring allreduce of the
    # 1/4 shard over 2 groups: 2*(1/2)*1024 = 1024
    assert c["wire_bytes"] == 6144 + 1024
    assert c["steps"] == 2 * 3 + 2 * 1
    # degenerate/invalid splits fall back to the plain op model
    flat = costmodel.cost("AllReduce", nbytes=4096, world=8,
                          dtype="float32", impl="hierarchical",
                          params={"fast": 3})
    assert flat["wire_bytes"] == 7168 and "impl" not in flat


def test_record_cost_reads_impl_stamp():
    rec = {"op": "AllReduce", "bytes": 4096, "world": 8,
           "dtype": "float32", "impl": "quantized"}
    assert costmodel.record_cost(rec)["wire_bytes"] == 3640
    del rec["impl"]
    assert costmodel.record_cost(rec)["wire_bytes"] == 7168


# ---------------------------------------------------------------------
# autotune: seed, refinement, gating
# ---------------------------------------------------------------------


def test_analytic_seed_is_deterministic_and_lossless():
    keys = autotune.default_keys(**GOLDEN_KEYS)
    a, _ = autotune.sweep(keys, gbps=25.0, alpha=1e-6)
    b, _ = autotune.sweep(keys, gbps=25.0, alpha=1e-6)
    assert a.plan_id == b.plan_id
    assert a.source == "analytic"
    assert all(e.impl not in planmod.LOSSY_IMPLS for e in a.entries.values())


def test_measured_data_overrides_the_analytic_seed():
    """Acceptance criterion: tune on a synthetic bandwidth table
    provably flips at least one plan key away from the analytic
    seed."""
    keys = autotune.default_keys(**GOLDEN_KEYS)
    seed, _ = autotune.sweep(keys, gbps=25.0, alpha=1e-6)
    tuned, report = autotune.sweep(keys, measured=GOLDEN_TABLE,
                                   gbps=25.0, alpha=1e-6)
    flipped = [k for k in seed.entries
               if tuned.entries[k].impl != seed.entries[k].impl]
    assert flipped, "the measured table must flip at least one key"
    for k in flipped:
        assert seed.entries[k].impl == "hlo"
        assert tuned.entries[k].impl == "pallas_ring"
        assert tuned.entries[k].source == "measured"
        assert tuned.entries[k].expected_gbps == 100.0
    assert tuned.source == "measured"
    # the report names both candidates with their analytic times
    row = next(r for r in report if r["key"] == flipped[0])
    impls = {c["impl"] for c in row["candidates"]}
    assert {"hlo", "pallas_ring"} <= impls


def test_pruning_drops_implausible_candidates_before_measurement():
    # a measured table praising an impl the model prunes must not
    # resurrect it: pruned candidates are never measured (the GC3
    # "only measure plausible candidates" move)
    key = planmod.plan_key("AllReduce", nbytes=16 << 20, dtype="float32",
                           world=8, axes=("ranks",), platform="cpu")
    table = {"schema": autotune.TABLE_SCHEMA, "gbps": {"quantized": 1e9}}
    planobj, report = autotune.sweep(
        [key], measured=table, allow_lossy=True, gbps=25.0, alpha=1e-6,
        prune=0.5,  # quantized moves ~4x fewer bytes: hlo gets pruned
    )
    (row,) = report
    pruned = {c["impl"] for c in row["candidates"] if c["pruned"]}
    assert "hlo" in pruned or "pallas_ring" in pruned
    for c in row["candidates"]:
        if c["pruned"]:
            assert c["measured_gbps"] is None


def test_lossy_needs_explicit_opt_in():
    keys = autotune.default_keys(**GOLDEN_KEYS)
    table = {"schema": autotune.TABLE_SCHEMA, "gbps": {"quantized": 1e6}}
    off, _ = autotune.sweep(keys, measured=table, gbps=25.0, alpha=1e-6)
    assert all(e.impl != "quantized" for e in off.entries.values())
    on, _ = autotune.sweep(keys, measured=table, allow_lossy=True,
                           gbps=25.0, alpha=1e-6)
    assert any(e.impl == "quantized" for e in on.entries.values())


def test_measured_table_from_events(tmp_path):
    # synthetic 1-rank run: impl-stamped emissions + latency samples
    path = tmp_path / "events-rank0.jsonl"
    with open(path, "w") as f:
        for seq, (impl, seconds) in enumerate(
            [("hlo", 0.001), ("hlo", 0.001), ("pallas_ring", 0.0001)], 1
        ):
            cid = f"c{seq}"
            f.write(json.dumps({
                "kind": "emission", "rank": 0, "seq": seq, "cid": cid,
                "op": "AllReduce", "bytes": 1 << 20, "dtype": "float32",
                "axes": ["ranks"], "world": 8, "impl": impl, "t": seq,
            }) + "\n")
            f.write(json.dumps({
                "kind": "latency", "rank": 0, "cid": cid, "op": "AllReduce",
                "seconds": seconds, "t": seq + 0.5,
            }) + "\n")
    table = autotune.measured_table_from_events(
        [str(tmp_path)], platform="cpu"
    )
    assert table["schema"] == autotune.TABLE_SCHEMA
    assert set(table["gbps"]) == {"hlo", "pallas_ring"}
    # the ring measured 10x faster on the same fingerprint
    assert table["gbps"]["pallas_ring"] > 5 * table["gbps"]["hlo"]
    key = planmod.plan_key("AllReduce", nbytes=1 << 20, dtype="float32",
                           world=8, axes=("ranks",), platform="cpu")
    assert key in table["keys"]
    keys = autotune.keys_from_events([str(tmp_path)], platform="cpu")
    assert keys == [key]


# ---------------------------------------------------------------------
# cache protocol: round-trip, atomicity, invalidation, restart
# ---------------------------------------------------------------------


def test_cache_roundtrip_and_merge(tmp_path):
    planobj = golden_plan()
    cache = tmp_path / "plan.json"
    planmod.save(planobj, str(cache))
    loaded = planmod.load(str(cache), platform="cpu")
    assert loaded.plan_id == planobj.plan_id
    assert {k: e.to_json() for k, e in loaded.entries.items()} == {
        k: e.to_json() for k, e in planobj.entries.items()
    }
    extra_key = "AllGather|b10|float32|w8|ranks|cpu"
    merged = planmod.merge(
        loaded,
        planmod.Plan(platform="cpu",
                     entries={extra_key: planmod.PlanEntry("hlo")}),
    )
    assert set(merged.entries) == set(loaded.entries) | {extra_key}
    # no tmp litter after the atomic rename
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []


@pytest.mark.parametrize("tamper,reason", [
    ("schema", "schema"),
    ("entries", "fingerprint"),
    ("platform_load", "topology"),
    ("torn", "parse"),
])
def test_cache_invalidation(tmp_path, tamper, reason):
    cache = tmp_path / "plan.json"
    planmod.save(golden_plan(), str(cache))
    data = json.load(open(cache))
    if tamper == "schema":
        data["schema"] = "m4t-plan/999"
    elif tamper == "entries":
        key = sorted(data["entries"])[0]
        data["entries"][key]["impl"] = "hierarchical"
    if tamper == "torn":
        open(cache, "w").write(json.dumps(data)[: len(json.dumps(data)) // 2])
    else:
        json.dump(data, open(cache, "w"))
    with pytest.raises(planmod.PlanError) as e:
        planmod.load(
            str(cache),
            platform="tpu:v5e" if tamper == "platform_load" else "cpu",
        )
    assert e.value.reason == reason


def test_pinned_plan_survives_process_restart(tmp_path):
    """Acceptance criterion: a tuned plan persisted via
    ``M4T_PLAN_CACHE`` re-arms in a *fresh process* and routes the
    pinned impl end to end (the pinned quantized ring shows up in the
    lowered HLO as collective-permutes instead of an all-reduce)."""
    key = planmod.plan_key("AllReduce", nbytes=4096 * 4, dtype="float32",
                           world=8, axes=("ranks",), platform="cpu")
    planobj = planmod.Plan(platform="cpu", entries={
        key: planmod.PlanEntry("quantized", source="measured"),
    })
    cache = tmp_path / "plan.json"
    planmod.save(planobj, str(cache))
    script = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import mpi4jax_tpu as m4t
from mpi4jax_tpu.parallel import spmd, world_mesh
from mpi4jax_tpu.planner import dispatch

assert dispatch.active is not None, "plan cache did not arm"
assert dispatch.active.plan_id == %(plan_id)r, dispatch.active.plan_id
mesh = world_mesh(8)
arr = np.arange(8 * 4096, dtype=np.float32).reshape(8, 4096)
fn = spmd(lambda x: m4t.allreduce(x), mesh=mesh)
text = jax.jit(lambda x: fn(x)).lower(jnp.asarray(arr)).as_text()
assert "collective_permute" in text, "quantized ring not routed"
assert "all_reduce" not in text, "HLO AllReduce still present"
out = np.asarray(fn(jnp.asarray(arr)))
exact = arr.sum(axis=0)
err = np.abs(out[0] - exact).max() / np.abs(exact).max()
assert err < 0.05, err
log = dispatch.decision_log()
assert log.get(%(key)r) == "quantized", log
print("restart-ok")
""" % {"plan_id": planobj.plan_id, "key": key}
    env = dict(
        os.environ,
        M4T_PLAN_CACHE=str(cache),
        M4T_PLATFORM_CLASS="cpu",
        MPI4JAX_TPU_SKIP_VERSION_CHECK="1",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "restart-ok" in proc.stdout


def test_invalid_cache_in_env_warns_and_stays_unarmed(tmp_path):
    cache = tmp_path / "plan.json"
    cache.write_text('{"schema": "m4t-plan/999", "entries": {}}')
    script = (
        "from mpi4jax_tpu.planner import dispatch\n"
        "assert dispatch.active is None\n"
        "print('unarmed-ok')\n"
    )
    env = dict(os.environ, M4T_PLAN_CACHE=str(cache),
               MPI4JAX_TPU_SKIP_VERSION_CHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "unarmed-ok" in proc.stdout
    assert "ignoring plan cache" in proc.stderr


# ---------------------------------------------------------------------
# golden plan-cache schema pin
# ---------------------------------------------------------------------


def test_plan_cache_golden_pin():
    """Literal pin of the persisted plan-cache JSON (the ``m4t-plan/1``
    schema): any change to the key format, entry fields, or fingerprint
    computation shows up as a diff here. Regen intentionally with
    ``python tests/test_planner.py --regen``."""
    got = golden_plan().to_json()
    with open(GOLDEN) as f:
        want = json.load(f)
    assert got == want, (
        "plan-cache schema drifted from tests/data/plan_golden.json; "
        "if intentional, regen with `python tests/test_planner.py "
        "--regen` and bump planner/plan.SCHEMA if the layout changed"
    )


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _run_cli(args, **env_extra):
    env = dict(os.environ, MPI4JAX_TPU_SKIP_VERSION_CHECK="1",
               JAX_PLATFORMS="cpu", **env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", *args],
        env=env, capture_output=True, text=True, timeout=300,
    )


def test_cli_selftest():
    proc = _run_cli(["--selftest"])
    assert proc.returncode == 0, proc.stderr
    assert "planner selftest ok" in proc.stdout


def test_cli_tune_show_roundtrip(tmp_path):
    table = tmp_path / "table.json"
    json.dump(GOLDEN_TABLE, open(table, "w"))
    cache = tmp_path / "plan.json"
    proc = _run_cli([
        "tune", "--cache", str(cache), "--world", "8",
        "--dtypes", "float32", "--buckets", "13,21,25",
        "--measured", str(table), "--platform", "cpu", "--json",
        "--peak-gbps", "25", "--alpha-us", "1",
    ])
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["plan"]["plan_id"] == golden_plan().plan_id
    assert cache.exists()

    show = _run_cli(["show", "--cache", str(cache)])
    assert show.returncode == 0, show.stderr
    assert golden_plan().plan_id in show.stdout
    assert "pallas_ring" in show.stdout

    # show on a torn cache: exit 1 with the reason
    cache.write_text("{broken")
    bad = _run_cli(["show", "--cache", str(cache)])
    assert bad.returncode == 1
    assert "[parse]" in bad.stderr


def test_cli_show_without_cache_is_usage_error():
    proc = _run_cli(["show"], M4T_PLAN_CACHE="")
    assert proc.returncode == 2


# ---------------------------------------------------------------------
# launch integration: --tune writes a plan, --plan re-arms it
# ---------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)

_TUNE_SCRIPT = """
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.runtime import shm
x = jnp.arange(2048.0) + shm.rank()
for _ in range(4):
    x = m4t.allreduce(x)
print(f"OK{shm.rank()}")
"""


@needs_native
def test_launch_tune_writes_plan_and_plan_rearms(tmp_path):
    """e2e: a 2-rank ``launch --events-dir --plan --tune`` run measures
    its own collectives, writes a validating plan cache whose keys are
    the run's emissions, and a second launch arms it via ``--plan``."""
    import textwrap

    case = str(tmp_path / "case.py")
    with open(case, "w") as f:
        f.write(f"import sys; sys.path.insert(0, {REPO!r})\n")
        f.write(textwrap.dedent(_TUNE_SCRIPT))
    rundir = str(tmp_path / "run")
    cache = str(tmp_path / "plan.json")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--events-dir", rundir, "--plan", cache, "--tune", case],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    assert "OK0" in res.stdout and "OK1" in res.stdout
    assert "--tune: pinned" in res.stderr, res.stderr
    planobj = planmod.load(cache, platform="cpu")
    keys = list(planobj.entries)
    assert keys, "tune pinned nothing"
    assert all(k.startswith("AllReduce|") for k in keys), keys
    assert all(k.endswith("|cpu") for k in keys), keys

    # second run arms the tuned plan in every rank
    res2 = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--plan", cache, case],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert res2.returncode == 0, res2.stderr

    # a torn cache blocks the launch before any rank spawns
    open(cache, "w").write("{broken")
    res3 = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--plan", cache, case],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert res3.returncode == 2, (res3.returncode, res3.stderr)
    assert "OK0" not in res3.stdout


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            json.dump(golden_plan().to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"regenerated {GOLDEN}")
    else:
        sys.exit(pytest.main([__file__, "-v"]))
