"""Control-plane observatory (PR 17): serving/profile.py.

Covers the ISSUE-17 acceptance surface:

- arming (the faults.py standard): ``M4T_CP_PROFILE`` armed at spool
  init, one falsy check per hot site unarmed, and the unarmed
  ``serving.jsonl`` record schemas byte-identical to PR 16 (drift
  pins) with no cp sink created at all;
- the micro-span stream: every instrumented phase lands in the
  ``m4t-cp/1`` vocabulary, claim races *lost* are counted under the
  threaded federation race fixture, wasted vs useful wakeups split;
- the queue-wait decomposition: per job, the named phases telescope
  to the ``queued`` span within tolerance at >= 90% coverage, on a
  real stub-runner drain;
- one dispatch-latency definition: ``profile.dispatch_durations`` is
  what both ``serve_loadgen`` and the profile report use, pinned
  equal here;
- surfaces: the ``serving profile`` CLI round-trip, ``m4t_cp_*``
  OpenMetrics families, per-server control-plane Perfetto tracks,
  doctor narration, the armed-overhead bound, and the
  ``M4T_POOL_POLL_S`` / ``--poll-interval`` satellite.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from mpi4jax_tpu.serving import export as sexport
from mpi4jax_tpu.serving import pool as pool_mod
from mpi4jax_tpu.serving import profile
from mpi4jax_tpu.serving.server import Server
from mpi4jax_tpu.serving.spool import Spool

pytestmark = [pytest.mark.cp_profile, pytest.mark.serving]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(module, *argv, env=None):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(
        [sys.executable, "-m", module, *argv],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env=full_env,
    )


def _drain(root, jobs=4, tenants=2, poll_s=0.01):
    """Submit + serve a stub mix; returns the spool."""
    spool = Spool(root)
    spool.configure(max(16, jobs))
    for i in range(jobs):
        r = spool.submit({
            "id": f"j{i}", "tenant": f"t{i % tenants}",
            "cmd": ["-c", "pass"],
        })
        assert r["status"] == "queued", r
    server = Server(
        spool, nproc=1, max_jobs=jobs, poll_s=poll_s,
        runner=lambda *a: (0, []), log=lambda msg: None,
    )
    assert server.serve() == 0
    return spool


@pytest.fixture
def armed(tmp_path, monkeypatch):
    """M4T_CP_PROFILE set, profiler reset before and after."""
    monkeypatch.setenv(profile.ENV_VAR, "1")
    monkeypatch.setattr(sexport, "CP_SNAPSHOT_TTL_S", 0.0)
    profile.disarm()
    yield str(tmp_path / "spool")
    profile.disarm()


@pytest.fixture
def disarmed(tmp_path, monkeypatch):
    monkeypatch.delenv(profile.ENV_VAR, raising=False)
    profile.disarm()
    yield str(tmp_path / "spool")
    profile.disarm()


# ---------------------------------------------------------------------
# unarmed drift pins: the PR 16 serving.jsonl schemas, literally
# ---------------------------------------------------------------------

#: adding a field to the *unarmed* serving stream is a breaking change
#: for every downstream reader and must be an intentional, reviewed
#: edit of these pins — the profiler writes to its own sink precisely
#: so these never move
UNARMED_AUDIT_KEYS = {
    "submitted": {"kind", "event", "job", "tenant", "nproc", "depth",
                  "trace", "t", "ts"},
    "claimed": {"kind", "event", "job", "tenant", "server", "epoch",
                "t", "ts"},
    "admitted": {"kind", "event", "job", "tenant", "world",
                 "requested_nproc", "queue_wait_s", "trace", "t", "ts"},
    "completed": {"kind", "event", "job", "tenant", "world", "attempts",
                  "queue_wait_s", "run_s", "t", "ts"},
}
UNARMED_SPAN_KEYS = {
    "queued": {"kind", "schema", "span", "job", "tenant", "trace",
               "t0", "t1", "dur_s", "depth_wait_s", "ts"},
    "dispatch": {"kind", "schema", "span", "job", "tenant", "trace",
                 "t0", "t1", "dur_s", "world", "ts"},
    "result": {"kind", "schema", "span", "job", "tenant", "trace",
               "t0", "t1", "dur_s", "outcome", "ts"},
}


def _schema_pins(spool):
    audits = {r["event"]: set(r) for r in spool.audit_records()
              if r["event"] in UNARMED_AUDIT_KEYS}
    spans = {r["span"]: set(r) for r in spool.span_records()
             if r["span"] in UNARMED_SPAN_KEYS}
    return audits, spans


def test_unarmed_schema_drift_pin_and_no_sink(disarmed):
    spool = _drain(disarmed, jobs=1)
    audits, spans = _schema_pins(spool)
    for event, keys in UNARMED_AUDIT_KEYS.items():
        assert audits[event] == keys, (event, sorted(audits[event]))
    for span, keys in UNARMED_SPAN_KEYS.items():
        assert spans[span] == keys, (span, sorted(spans[span]))
    # the whole point of the separate sink: unarmed leaves no trace
    assert profile.profile_paths(spool.root) == []
    assert profile.active is None


def test_armed_run_leaves_serving_schemas_identical(armed):
    """Arming adds a *sibling* file; the audit/span records the rest
    of the system parses do not change by a single key."""
    spool = _drain(armed, jobs=1)
    audits, spans = _schema_pins(spool)
    for event, keys in UNARMED_AUDIT_KEYS.items():
        assert audits[event] == keys, (event, sorted(audits[event]))
    for span, keys in UNARMED_SPAN_KEYS.items():
        assert spans[span] == keys, (span, sorted(spans[span]))
    assert profile.profile_paths(spool.root) == [
        os.path.join(spool.root, profile.PROFILE_NAME)
    ]


def test_arming_standard(tmp_path, monkeypatch):
    monkeypatch.delenv(profile.ENV_VAR, raising=False)
    profile.disarm()
    assert profile.arm_from_env(str(tmp_path)) is None
    assert profile.active is None
    monkeypatch.setenv(profile.ENV_VAR, "1")
    prof = profile.arm_from_env(str(tmp_path / "a"))
    assert prof is profile.active
    # same root: no re-arm; new root: latest spool wins
    assert profile.arm_from_env(str(tmp_path / "a")) is prof
    assert profile.arm_from_env(str(tmp_path / "b")) is not prof
    profile.disarm()
    assert profile.active is None


def test_cp_record_drops_none_fields():
    rec = profile.cp_record(
        "claim", dur_s=0.5, t=100.0, job="j1", server=None,
    )
    assert set(rec) == {"kind", "schema", "phase", "t", "dur_s", "job"}
    assert rec["schema"] == profile.CP_SCHEMA
    assert rec["dur_s"] == 0.5


# ---------------------------------------------------------------------
# the micro-span stream
# ---------------------------------------------------------------------


def test_phases_stay_in_vocabulary(armed):
    spool = _drain(armed, jobs=3)
    cp = profile.load_cp(spool.root)
    assert cp
    seen = {r["phase"] for r in cp}
    assert seen <= profile.PHASES, sorted(seen - profile.PHASES)
    for needed in ("submit", "submit.scan", "submit.write",
                   "submit.fsync", "submit.rename", "claim",
                   "sched.pick", "loop.scan", "loop.wakeup",
                   "finish", "finish.fsync", "finish.rename"):
        assert needed in seen, (needed, sorted(seen))
    # wall-ordered, schema-stamped, non-negative durations
    ts = [r["t"] for r in cp]
    assert ts == sorted(ts)
    assert all(r["schema"] == profile.CP_SCHEMA for r in cp)
    assert all(r["dur_s"] >= 0 for r in cp)


def test_claim_races_lost_counted(armed):
    """The threaded federation race fixture: N servers racing claim
    over M jobs — every losing rename lands as a ``claim.lost``
    record attributed to the losing server."""
    spool = Spool(armed)
    spool.configure(64)
    jobs = [f"j{i:02d}" for i in range(8)]
    for j in jobs:
        assert spool.submit({"id": j, "cmd": ["-c", "pass"]})[
            "status"] == "queued"
    n = 6
    barrier = threading.Barrier(n)

    def racer(i):
        specs = spool.pending()  # private spec objects per thread
        barrier.wait()
        for spec in specs:
            spool.claim(spec, server=f"s{i}")

    threads = [threading.Thread(target=racer, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    cp = profile.load_cp(spool.root)
    won = [r for r in cp if r["phase"] == "claim"]
    lost = [r for r in cp if r["phase"] == "claim.lost"]
    assert len(won) == len(jobs)
    assert len(lost) == (n - 1) * len(jobs)  # every attempt recorded
    assert {r["server"] for r in won + lost} <= {
        f"s{i}" for i in range(n)
    }
    report = profile.profile_report(spool.root)
    assert report["claims"]["won"] == len(jobs)
    assert report["claims"]["lost"] == len(lost)
    assert report["claims"]["lost_ratio"] == pytest.approx(
        len(lost) / (len(won) + len(lost)), abs=1e-4,
    )


def test_wakeup_split_useful_vs_wasted(armed):
    spool = Spool(armed)
    server = Server(
        spool, nproc=1, idle_exit_s=0.15, poll_s=0.02,
        runner=lambda *a: (0, []), log=lambda msg: None,
    )
    assert server.serve() == 0
    report = profile.profile_report(spool.root)
    wk = report["wakeups"]["server"]
    assert wk["total"] > 0 and wk["useful"] == 0
    assert wk["wasted_ratio"] == 1.0


# ---------------------------------------------------------------------
# queue-wait decomposition
# ---------------------------------------------------------------------


def test_decomposition_sums_to_queue_span(armed):
    """Property: for every job in a real stub drain the named phases
    telescope to the ``queued`` span within SUM_TOLERANCE_S, with the
    residual (hand-off) sliver under 10%."""
    spool = _drain(armed, jobs=6, tenants=3)
    decomps = profile.decompose(spool.root)
    assert len(decomps) == 6
    for d in decomps:
        assert d["ok"], d
        assert abs(d["sum_s"] - d["queue_wait_s"]) <= (
            profile.SUM_TOLERANCE_S
        ), d
        assert set(d["phases"]) == set(profile.QUEUE_PHASES)
        assert d["coverage"] >= 0.90, d
        assert all(v >= 0 for v in d["phases"].values()), d


def test_decomposition_without_scheduler_record(armed):
    """A bare ``spool.claim`` (no scheduler pick) still decomposes:
    the rename is charged and the telescoping identity holds."""
    spool = Spool(armed)
    r = spool.submit({"id": "jx", "cmd": ["-c", "pass"]})
    assert r["status"] == "queued"
    (spec,) = spool.pending()
    got = spool.claim(spec)
    assert got is not None
    spool.span(
        "queued", job=got.id, t0=spec.submitted_t, t1=time.time(),
        tenant=got.tenant,
    )
    decomps = profile.decompose(spool.root)
    (d,) = decomps
    assert d["ok"], d
    assert d["phases"]["sched_pick"] == 0


def test_narration_names_dominant_phases(armed):
    spool = _drain(armed, jobs=2)
    for d in profile.decompose(spool.root):
        line = profile.narrate_job(d)
        assert line.startswith(f"job {d['job']}: queue-wait")
        assert "%" in line


def test_one_dispatch_definition(armed):
    """Satellite: serve_loadgen's dispatch percentiles and the profile
    report's come from profile.dispatch_durations — one definition."""
    spool = _drain(armed, jobs=4)
    spans = spool.span_records()
    durs = profile.dispatch_durations(spans)
    inline = sorted(  # the pre-PR-17 inline definition
        float(s.get("dur_s") or 0.0)
        for s in spans if s.get("span") == "dispatch"
    )
    assert durs == inline and len(durs) == 4
    report = profile.profile_report(spool.root)
    assert report["dispatch_p50_s"] == profile._pct(durs, 0.50)
    assert report["dispatch_p99_s"] == profile._pct(durs, 0.99)


def test_loadgen_profile_mode_uses_same_definition(armed):
    """benchmarks/serve_loadgen.py --profile: the BENCH record's
    dispatch numbers equal the cp report's for the same drain."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(REPO, "benchmarks",
                                      "serve_loadgen.py"),
    )
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    result = lg.run_loadgen(4, 2, 1, stub=True, queue_cap=8)
    cp = result["cp"]
    assert cp is not None and cp["records"] > 0
    assert result["dispatch_p50_s"] == cp["dispatch_p50_s"]
    assert result["dispatch_p99_s"] == cp["dispatch_p99_s"]


# ---------------------------------------------------------------------
# syscall budget
# ---------------------------------------------------------------------


def test_syscall_budget_per_job(armed):
    spool = _drain(armed, jobs=4)
    sc = profile.profile_report(spool.root)["syscalls"]
    assert sc["jobs"] == 4
    # per dispatched job: submit fsync + finish fsync
    assert sc["fsyncs_per_job"] == 2.0
    # submit rename + claim rename + fence + done rename
    assert sc["renames_per_job"] == 4.0
    # 5 submit scans + the serve loop's pending scans
    assert sc["dir_scans_per_job"] >= 5.0


# ---------------------------------------------------------------------
# surfaces: CLI, OpenMetrics, Perfetto, doctor
# ---------------------------------------------------------------------


def test_profile_cli_round_trip(armed):
    spool = _drain(armed, jobs=2)
    env = {profile.ENV_VAR: "1",
           "MPI4JAX_TPU_SKIP_VERSION_CHECK": "1",
           "JAX_PLATFORMS": "cpu"}
    p = _run_cli("mpi4jax_tpu.serving", "profile", spool.root,
                 "--json", env=env)
    assert p.returncode == 0, p.stderr
    report = json.loads(p.stdout)
    assert report["schema"] == profile.REPORT_SCHEMA
    assert report["records"] == len(profile.load_cp(spool.root))
    assert report["claims"]["won"] == 2
    p = _run_cli("mpi4jax_tpu.serving", "profile", spool.root, env=env)
    assert p.returncode == 0, p.stderr
    assert "phase latency" in p.stdout
    assert "syscall budget" in p.stdout
    assert "queue-wait decomposition" in p.stdout


def test_profile_cli_empty_spool_exits_2(disarmed):
    spool = Spool(disarmed)
    p = _run_cli("mpi4jax_tpu.serving", "profile", spool.root,
                 env={"MPI4JAX_TPU_SKIP_VERSION_CHECK": "1",
                      "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 2
    assert profile.ENV_VAR in p.stderr


def test_openmetrics_families(armed):
    spool = _drain(armed, jobs=2)
    snap = sexport.serving_snapshot(spool)
    assert snap["cp"] is not None
    text = sexport.render_serving_metrics(snap)
    for family in ("m4t_cp_phase_seconds", "m4t_cp_phase_ops_total",
                   "m4t_cp_fsync_total", "m4t_cp_rename_total",
                   "m4t_cp_dir_scan_total",
                   "m4t_cp_poll_wakeups_total",
                   "m4t_cp_claim_races_lost_total"):
        assert f"# TYPE {family}" in text, family
    assert 'phase="claim",quantile="p50"' in text
    assert 'plane="server",useful="true"' in text
    assert text.rstrip().endswith("# EOF")


def test_openmetrics_absent_when_unarmed(disarmed):
    spool = _drain(disarmed, jobs=1)
    snap = sexport.serving_snapshot(spool)
    assert snap["cp"] is None
    assert "m4t_cp_" not in sexport.render_serving_metrics(snap)


def test_trace_serve_controlplane_tracks(armed, tmp_path):
    from mpi4jax_tpu.observability import trace

    spool = _drain(armed, jobs=2)
    out = str(tmp_path / "trace.json")
    obj = trace.export_serve(spool.root, out)
    assert obj is not None
    tracks = obj["otherData"]["controlplane"]
    assert any(t["track"].startswith("server ") for t in tracks)
    assert any(t["track"] == "submit" for t in tracks)
    # cp pids start after every job's pid block — no collisions
    job_pid_ceiling = len(obj["otherData"]["jobs"]) * trace.JOB_PID_STRIDE
    assert all(t["pid"] >= job_pid_ceiling for t in tracks)
    cp_slices = [e for e in obj["traceEvents"]
                 if e.get("ph") == "X"
                 and e["pid"] >= job_pid_ceiling]
    assert {e["name"] for e in cp_slices} >= {"submit.fsync",
                                              "sched.pick", "claim"}
    assert all(e["ts"] >= 0 for e in cp_slices)


def test_trace_serve_unarmed_has_no_controlplane_key(disarmed, tmp_path):
    """An unarmed spool's merged export stays byte-compatible with the
    PR 12 golden — the controlplane key is armed-only."""
    from mpi4jax_tpu.observability import trace

    spool = _drain(disarmed, jobs=1)
    obj = trace.export_serve(spool.root, str(tmp_path / "t.json"))
    assert obj is not None
    assert "controlplane" not in obj["otherData"]


def test_doctor_narrates_queue_wait(armed):
    spool = _drain(armed, jobs=2)
    p = _run_cli("mpi4jax_tpu.observability.doctor", spool.root,
                 env={"MPI4JAX_TPU_SKIP_VERSION_CHECK": "1",
                      "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr
    assert "control-plane profile" in p.stdout
    assert "queue-wait" in p.stdout
    assert "syscall budget" in p.stdout


def test_selftest_entrypoint():
    p = _run_cli("mpi4jax_tpu.serving.profile", "--selftest",
                 env={"MPI4JAX_TPU_SKIP_VERSION_CHECK": "1",
                      "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr
    assert "cp profile selftest ok" in p.stdout


# ---------------------------------------------------------------------
# overhead bound
# ---------------------------------------------------------------------


def test_armed_overhead_bounded(tmp_path, monkeypatch):
    """The armed stub drain — the worst case, where control-plane cost
    is 100% of the work — stays within a generous CI bound of the
    disarmed drain (the BENCH trajectory documents the real ~0-5%)."""
    import time

    def drain_wall(arm, root):
        if arm:
            monkeypatch.setenv(profile.ENV_VAR, "1")
        else:
            monkeypatch.delenv(profile.ENV_VAR, raising=False)
        profile.disarm()
        t0 = time.monotonic()
        _drain(root, jobs=8)
        return time.monotonic() - t0

    try:
        base = min(
            drain_wall(False, str(tmp_path / "d1")),
            drain_wall(False, str(tmp_path / "d2")),
        )
        armed_wall = min(
            drain_wall(True, str(tmp_path / "a1")),
            drain_wall(True, str(tmp_path / "a2")),
        )
    finally:
        profile.disarm()
    assert armed_wall <= base * 2.5 + 0.25, (armed_wall, base)


# ---------------------------------------------------------------------
# satellite: configurable poll intervals
# ---------------------------------------------------------------------


def test_resolve_poll_s_precedence(monkeypatch):
    monkeypatch.delenv(pool_mod.POLL_ENV, raising=False)
    assert pool_mod.resolve_poll_s(None, 0.02) == 0.02
    assert pool_mod.resolve_poll_s(0.5, 0.02) == 0.5
    monkeypatch.setenv(pool_mod.POLL_ENV, "0.005")
    assert pool_mod.resolve_poll_s(None, 0.02) == 0.005
    # explicit beats env
    assert pool_mod.resolve_poll_s(0.1, 0.02) == 0.1
    with pytest.raises(ValueError):
        pool_mod.resolve_poll_s(0.0, 0.02)
    with pytest.raises(ValueError):
        pool_mod.resolve_poll_s(-1.0, 0.02)


def test_resolve_poll_s_invalid_env_falls_back(monkeypatch, capsys):
    for bad in ("nope", "-3", "0"):
        monkeypatch.setenv(pool_mod.POLL_ENV, bad)
        assert pool_mod.resolve_poll_s(None, 0.02) == 0.02
        assert pool_mod.POLL_ENV in capsys.readouterr().err


def test_worker_pool_reads_poll_env(tmp_path, monkeypatch):
    monkeypatch.setenv(pool_mod.POLL_ENV, "0.004")
    pool = pool_mod.WorkerPool(
        str(tmp_path / "pool"), 1, audit=lambda *a, **k: None,
        log=lambda m: None,
    )
    assert pool.poll_s == 0.004


def test_server_rejects_nonpositive_poll(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    with pytest.raises(ValueError, match="poll_s"):
        Server(spool, nproc=1, poll_s=0.0, log=lambda m: None)


def test_serve_cli_poll_interval_alias(tmp_path):
    p = _run_cli(
        "mpi4jax_tpu.serving", "serve", str(tmp_path / "sp"),
        "-n", "1", "--poll-interval", "0.01", "--max-jobs", "0",
        env={"MPI4JAX_TPU_SKIP_VERSION_CHECK": "1",
             "JAX_PLATFORMS": "cpu"},
    )
    assert p.returncode == 0, p.stderr
