"""The mpi4jax source-compat shim: reference-style code runs verbatim
(modulo the documented SPMD table/shape deltas)."""

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu.compat as mpi4jax
from mpi4jax_tpu.compat import MPI

N = 8


def test_reference_readme_example(run_spmd, per_rank):
    # the reference README example (README.rst:59-88), verbatim shape
    comm = MPI.COMM_WORLD

    def foo(arr):
        arr = arr + comm.Get_rank().astype(arr.dtype)
        arr_sum = mpi4jax.allreduce(arr, op=MPI.SUM, comm=comm)
        return arr_sum

    arr = per_rank(lambda r: np.zeros((3, 3), np.float32))
    out = run_spmd(lambda a: jax.jit(foo)(a), arr)
    expected = np.full((3, 3), sum(range(N)), np.float32)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected)


def test_op_constants_and_sentinels():
    assert MPI.SUM.name == "SUM" and MPI.PROD.name == "PROD"
    assert MPI.PROC_NULL == -1 and MPI.ANY_TAG == -1
    assert mpi4jax.has_cuda_support() is False


def test_comm_world_eager_size1():
    out = mpi4jax.bcast(jnp.arange(4.0), 0, comm=MPI.COMM_WORLD)
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


def test_mpi_namespace_surface():
    # the mpi4py.MPI lookalike exposes everything reference scripts use
    import mpi4jax_tpu as m4t

    assert MPI.SUM is m4t.SUM and MPI.PROD is m4t.PROD
    assert MPI.PROC_NULL == m4t.PROC_NULL and MPI.ANY_TAG == m4t.ANY_TAG
    assert MPI.ANY_SOURCE is m4t.ANY_SOURCE
    st = MPI.Status()
    assert hasattr(st, "Get_source") and hasattr(st, "Get_count")
    from mpi4jax_tpu.runtime import shm as _shm

    world = _shm.size() if _shm.active() else 1
    assert MPI.COMM_WORLD.Get_size() == world  # eager world size


def test_comm_portability_noops():
    # mpi4py scripts commonly call Free()/Get_name(); both must be safe
    c = MPI.COMM_WORLD
    assert c.Get_name() == "MPI_COMM_WORLD"  # mpi4py default-name parity
    c.Free()  # no-op, no error
    d = c.Clone()
    d.Free()
    import mpi4jax_tpu as m4t

    assert "CartComm" in m4t.CartComm(dims=(2, 4)).Get_name()


def test_porting_checklist_errors():
    # The four SPMD contract deviations a ported reference script can
    # hit (docs/sharp-bits.md "Porting checklist") must each fail with
    # the documented, actionable error.
    import jax.numpy as jnp
    import numpy as np
    import pytest

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu.parallel import spmd

    from tests.conftest import WORLD, needs_size1_world  # noqa: F401

    N = 8
    x = jnp.ones(3)

    # 1. bare-int partner at size > 1 -> per-rank table demanded
    @spmd
    def bare_int(v):
        return m4t.sendrecv(v, v, source=1, dest=1)

    with pytest.raises(ValueError, match="per-rank table"):
        bare_int(jnp.ones((N, 3)))

    # 2. distinct sendrecv tags -> must agree (fused transfer)
    ring_dst = tuple((r + 1) % N for r in range(N))
    ring_src = tuple((r - 1) % N for r in range(N))

    @spmd
    def two_tags(v):
        return m4t.sendrecv(v, v, ring_src, ring_dst, sendtag=1, recvtag=2)

    with pytest.raises(ValueError, match="must equal sendtag"):
        two_tags(jnp.ones((N, 3)))

    # 3. scatter without the full (size, ...) input on the XLA path
    @spmd
    def bad_scatter(v):
        return m4t.scatter(v, 0)

    with pytest.raises(ValueError, match="leading axis"):
        bad_scatter(jnp.ones((N, 3)))

    # 4. unequal Split groups bound on the XLA path
    uneven = m4t.GroupComm(((0, 1, 2), (3,), (4, 5, 6, 7)))

    @spmd
    def uneven_allreduce(v):
        return m4t.allreduce(v, op=m4t.SUM, comm=uneven)

    with pytest.raises(ValueError, match="equal size"):
        uneven_allreduce(jnp.ones((N, 3)))
