"""The mpi4jax source-compat shim: reference-style code runs verbatim
(modulo the documented SPMD table/shape deltas)."""

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu.compat as mpi4jax
from mpi4jax_tpu.compat import MPI

N = 8


def test_reference_readme_example(run_spmd, per_rank):
    # the reference README example (README.rst:59-88), verbatim shape
    comm = MPI.COMM_WORLD

    def foo(arr):
        arr = arr + comm.Get_rank().astype(arr.dtype)
        arr_sum = mpi4jax.allreduce(arr, op=MPI.SUM, comm=comm)
        return arr_sum

    arr = per_rank(lambda r: np.zeros((3, 3), np.float32))
    out = run_spmd(lambda a: jax.jit(foo)(a), arr)
    expected = np.full((3, 3), sum(range(N)), np.float32)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected)


def test_op_constants_and_sentinels():
    assert MPI.SUM.name == "SUM" and MPI.PROD.name == "PROD"
    assert MPI.PROC_NULL == -1 and MPI.ANY_TAG == -1
    assert mpi4jax.has_cuda_support() is False


def test_comm_world_eager_size1():
    out = mpi4jax.bcast(jnp.arange(4.0), 0, comm=MPI.COMM_WORLD)
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


def test_mpi_namespace_surface():
    # the mpi4py.MPI lookalike exposes everything reference scripts use
    import mpi4jax_tpu as m4t

    assert MPI.SUM is m4t.SUM and MPI.PROD is m4t.PROD
    assert MPI.PROC_NULL == m4t.PROC_NULL and MPI.ANY_TAG == m4t.ANY_TAG
    assert MPI.ANY_SOURCE is m4t.ANY_SOURCE
    st = MPI.Status()
    assert hasattr(st, "Get_source") and hasattr(st, "Get_count")
    from mpi4jax_tpu.runtime import shm as _shm

    world = _shm.size() if _shm.active() else 1
    assert MPI.COMM_WORLD.Get_size() == world  # eager world size


def test_comm_portability_noops():
    # mpi4py scripts commonly call Free()/Get_name(); both must be safe
    c = MPI.COMM_WORLD
    assert c.Get_name() == "MPI_COMM_WORLD"  # mpi4py default-name parity
    c.Free()  # no-op, no error
    d = c.Clone()
    d.Free()
    import mpi4jax_tpu as m4t

    assert "CartComm" in m4t.CartComm(dims=(2, 4)).Get_name()
