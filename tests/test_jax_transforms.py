"""Communication primitives inside jax control flow and library
solvers — analog of the reference's ``tests/test_jax_transforms.py:6-22``
(CG solve through an allreduce operator, exercising effects inside
``jax.scipy`` / ``lax`` control flow) plus scan/while_loop coverage."""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4t

N = 8
DIM = N * 2


def test_cg_solve_through_allreduce(run_spmd):
    # SPD system solved by jax.scipy CG where the operator contains a
    # collective.
    rng = np.random.RandomState(0)
    M = rng.rand(DIM, DIM).astype(np.float32)
    A = M @ M.T + DIM * np.eye(DIM, dtype=np.float32)
    b = rng.rand(DIM).astype(np.float32)
    k = DIM // N
    A_cols = np.stack([A[:, r * k : (r + 1) * k] for r in range(N)])
    b_rows = np.stack([b[r * k : (r + 1) * k] for r in range(N)])

    def solve(A_loc, b_loc):
        rank = m4t.get_default_comm().Get_rank()

        def matvec(x_full):
            x_loc = jax.lax.dynamic_slice(x_full, (rank * k,), (k,))
            return m4t.allreduce(A_loc @ x_loc, op=m4t.SUM)

        b_full = m4t.allgather(b_loc).reshape(-1)
        x, _ = jax.scipy.sparse.linalg.cg(matvec, b_full, tol=1e-6, maxiter=200)
        return x

    out = run_spmd(solve, A_cols, b_rows)
    expected = np.linalg.solve(A, b)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-2, atol=1e-3)


def test_collectives_inside_lax_scan(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r + 1))

    def f(x):
        def body(carry, _):
            carry = m4t.allreduce(carry, op=m4t.SUM) / N
            return carry, carry

        final, hist = jax.lax.scan(body, x, None, length=4)
        return final, hist

    final, hist = run_spmd(f, arr)
    # average is a fixed point after the first application
    mean = arr.mean()
    np.testing.assert_allclose(final, np.full(N, mean), rtol=1e-5)
    assert hist.shape == (N, 4)


def test_collectives_inside_while_loop(run_spmd, per_rank):
    arr = per_rank(lambda r: np.float32(r))

    def f(x):
        def cond(carry):
            i, _ = carry
            return i < 3

        def body(carry):
            i, v = carry
            return i + 1, m4t.allreduce(v, op=m4t.MAX)

        _, v = jax.lax.while_loop(cond, body, (0, x))
        return v

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, np.full(N, arr.max()))


def test_sendrecv_inside_fori_loop(run_spmd, per_rank):
    # ring rotation N times returns each value home
    arr = per_rank(lambda r: np.float32(r * 10))
    dst = tuple((r + 1) % N for r in range(N))
    src = tuple((r - 1) % N for r in range(N))

    def f(x):
        return jax.lax.fori_loop(
            0, N, lambda _, v: m4t.sendrecv(v, v, src, dst), x
        )

    out = run_spmd(f, arr)
    np.testing.assert_allclose(out, arr)
