"""Fused Pallas shallow-water step vs the composable XLA step.

The fused kernel (``models/fused_step.py``) collapses the whole AB2
step into one Pallas pass and is the single-chip benchmark hot path
(``bench.py``). Its correctness contract is *algebraic equivalence*
with :meth:`ShallowWaterModel.step` (reference physics
``shallow_water.py:270-403``): in float64 the two trajectories must
agree to reordering error (~1e-13), which a boundary/indexing bug
cannot hide under. f64 requires ``jax_enable_x64`` before backend
init, so that check runs in a subprocess like ``test_x64_ops.py``;
the in-process tests cover the f32 interpret path, pad/crop plumbing
and the guard rails.
"""

import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.models import fused_step as fs
from mpi4jax_tpu.models.shallow_water import (
    ModelState,
    ShallowWaterConfig,
    ShallowWaterModel,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_model():
    cfg = ShallowWaterConfig(nx=48, ny=30, dims=(1, 1))
    model = ShallowWaterModel(cfg)
    state = ModelState(*(jnp.asarray(b[0]) for b in model.initial_state_blocks()))
    return cfg, model, state


def test_pad_crop_roundtrip():
    cfg, _, state = _small_model()
    padded = fs.pad_state(cfg, state, 8)
    assert padded.h.shape == (fs.padded_rows(cfg, 8), fs.padded_cols(cfg))
    back = fs.crop_state(cfg, padded)
    for a, b in zip(state, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_matches_xla_step_f32_interpret():
    cfg, model, state = _small_model()
    ref = model.step(state, first_step=True)
    cur = fs.pad_state(cfg, ref, 8)
    for n in range(1, 5):
        ref = model.step(ref)
        cur = fs.fused_step(cfg, cur, block_rows=8, interpret=True)
        got = fs.crop_state(cfg, cur)
        for name, a, b in zip(ModelState._fields, ref, got):
            d = float(jnp.max(jnp.abs(a - b)))
            scale = 1.0 + float(jnp.max(jnp.abs(a)))
            assert d / scale < 1e-5, (n, name, d)


def test_fused_multistep_equals_repeated_steps():
    cfg, model, state = _small_model()
    state = model.step(state, first_step=True)
    pad = fs.pad_state(cfg, state, 8)
    a = fs.fused_multistep(cfg, pad, 3, block_rows=8, interpret=True)
    b = pad
    for _ in range(3):
        b = fs.fused_step(cfg, b, block_rows=8, interpret=True)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=0, atol=0)


def test_fused_two_steps_per_pass_matches_xla_f32_interpret():
    """Temporal blocking: one steps_per_pass=2 kernel pass must track
    two XLA steps — the halo margin (HALO=8 >= 2 radius-3 steps)
    makes the chained in-slab step exact, not an approximation."""
    cfg, model, state = _small_model()
    ref = model.step(state, first_step=True)
    cur = fs.pad_state(cfg, ref, 8)
    for n in range(1, 4):
        ref = model.step(model.step(ref))
        cur = fs.fused_step(
            cfg, cur, block_rows=8, interpret=True, steps_per_pass=2
        )
        got = fs.crop_state(cfg, cur)
        for name, a, b in zip(ModelState._fields, ref, got):
            d = float(jnp.max(jnp.abs(a - b)))
            scale = 1.0 + float(jnp.max(jnp.abs(a)))
            assert d / scale < 1e-5, (n, name, d)


def test_fused_multistep_spp2_handles_odd_counts():
    """fused_multistep(spp=2) must finish an odd span with a
    single-step pass and stay on the single-step trajectory."""
    cfg, model, state = _small_model()
    state = model.step(state, first_step=True)
    pad = fs.pad_state(cfg, state, 8)
    a = fs.fused_multistep(cfg, pad, 5, block_rows=8, interpret=True,
                           steps_per_pass=2)
    b = pad
    for _ in range(5):
        b = fs.fused_step(cfg, b, block_rows=8, interpret=True)
    for name, x, y in zip(ModelState._fields, a, b):
        d = float(jnp.max(jnp.abs(x - y)))
        scale = 1.0 + float(jnp.max(jnp.abs(y)))
        assert d / scale < 1e-6, (name, d)


def test_steps_per_pass_halo_guard():
    # deeper passes deepen the halo (halo_for), and block_rows below
    # the deepened halo is rejected instead of computing garbage
    assert [fs.halo_for(s) for s in (1, 2, 3, 4, 5, 6)] == \
        [8, 8, 16, 16, 16, 24]
    cfg, _, state = _small_model()
    padded = fs.pad_state(cfg, state, 8)
    with pytest.raises(ValueError, match="multiple of 8, >= 16"):
        fs.fused_step(cfg, padded, block_rows=8, interpret=True,
                      steps_per_pass=3)


@pytest.mark.parametrize("spp", [4, 5])
def test_fused_deep_steps_per_pass_matches_xla_f32_interpret(spp):
    """Deep temporal blocking (halo=16 covers up to five chained
    radius-3 steps): one kernel pass must track spp XLA steps."""
    cfg = ShallowWaterConfig(nx=48, ny=64, dims=(1, 1))
    model = ShallowWaterModel(cfg)
    state = ModelState(
        *(jnp.asarray(b[0]) for b in model.initial_state_blocks())
    )
    ref = model.step(state, first_step=True)
    cur = fs.pad_state(cfg, ref, 16)
    for _ in range(spp):
        ref = model.step(ref)
    cur = fs.fused_step(cfg, cur, block_rows=16, interpret=True,
                        steps_per_pass=spp)
    got = fs.crop_state(cfg, cur)
    for name, a, b in zip(ModelState._fields, ref, got):
        d = float(jnp.max(jnp.abs(a - b)))
        scale = 1.0 + float(jnp.max(jnp.abs(a)))
        assert d / scale < 1e-5, (name, d)


def test_vmem_compile_fence_on_benchmark_width():
    """The empirical compile fence: at the published benchmark width
    (nx_pad=3712) block_rows=160 stays compilable, the sizes that died
    in the r4 sweep (200/240/320) are fenced out."""
    cfg = ShallowWaterConfig(nx=3600, ny=1800, dims=(1, 1))
    assert fs.padded_cols(cfg) == 3712
    assert fs.block_rows_compilable(cfg, 160)
    for b in (200, 240, 320):
        assert fs.block_rows_legal(cfg.ny_local, b)
        assert not fs.block_rows_compilable(cfg, b)


def test_fit_block_rows_visits_all_multiples_of_8():
    """Regression: the old halving search (160->80->40->20->10) skipped
    every legal size for small extended grids, e.g. the 36 extended
    rows of a (6,1) row decomposition of ny=180 (`--nproc 6 --decomp
    rows` of the default example grid)."""
    got = fs.fit_block_rows(36, 160)
    assert got is not None and got % 8 == 0
    assert fs.block_rows_legal(36, got)
    # the result is the *largest* legal size, not just any legal one
    for b in range(got + 8, 161, 8):
        assert not fs.block_rows_legal(36, b)
    # and the decomposition from the advisory reproduces end-to-end
    from mpi4jax_tpu.models.fused_spmd import FusedRowDecomp

    cfg = ShallowWaterConfig(nx=360, ny=180, dims=(6, 1))
    stepper = FusedRowDecomp(cfg)
    assert fs.block_rows_legal(stepper.ext_rows, stepper.block_rows)


def test_guard_rails():
    cfg, model, state = _small_model()
    padded = fs.pad_state(cfg, state, 8)
    with pytest.raises(ValueError, match="multiple of 8"):
        fs.fused_step(cfg, padded, block_rows=12, interpret=True)
    with pytest.raises(ValueError, match="two row tiles"):
        fs.fused_step(cfg, padded, block_rows=32, interpret=True)
    # nyp < block_rows + 2*HALO would invert the DMA-window clamp and
    # compute a negative (out-of-bounds) slab offset
    tiny = ShallowWaterConfig(nx=48, ny=14, dims=(1, 1))
    tiny_model = ShallowWaterModel(tiny)
    tiny_state = ModelState(
        *(jnp.asarray(b[0]) for b in tiny_model.initial_state_blocks())
    )
    tiny_pad = fs.pad_state(tiny, tiny_state, 8)
    with pytest.raises(ValueError, match="two row tiles"):
        fs.fused_step(tiny, tiny_pad, block_rows=8, interpret=True)
    spmd_cfg = ShallowWaterConfig(nx=48, ny=30, dims=(2, 1))
    with pytest.raises(NotImplementedError, match="single-rank"):
        fs.fused_step(spmd_cfg, padded, block_rows=8, interpret=True)
    walls = ShallowWaterConfig(nx=48, ny=30, dims=(1, 1), periodic_x=False)
    with pytest.raises(NotImplementedError, match="periodic_x"):
        fs.fused_step(walls, padded, block_rows=8, interpret=True)


_F64_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models import fused_step as fs

cfg = ShallowWaterConfig(nx=48, ny=30, dims=(1, 1), dtype=np.float64)
model = ShallowWaterModel(cfg)
state = ModelState(
    *(jnp.asarray(b[0], jnp.float64) for b in model.initial_state_blocks())
)
ref = model.step(state, first_step=True)
cur = fs.pad_state(cfg, ref, 8)
cur2 = cur
worst = worst2 = 0.0
for n in range(8):
    ref = model.step(ref)
    cur = fs.fused_step(cfg, cur, block_rows=8, interpret=True)
    got = fs.crop_state(cfg, cur)
    for a, b in zip(ref, got):
        d = float(jnp.max(jnp.abs(a - b)))
        worst = max(worst, d / (1.0 + float(jnp.max(jnp.abs(a)))))
    if n % 2 == 1:  # temporally blocked path advances two at a time
        cur2 = fs.fused_step(cfg, cur2, block_rows=8, interpret=True,
                             steps_per_pass=2)
        got2 = fs.crop_state(cfg, cur2)
        for a, b in zip(ref, got2):
            d = float(jnp.max(jnp.abs(a - b)))
            worst2 = max(worst2, d / (1.0 + float(jnp.max(jnp.abs(a)))))
assert worst < 1e-12, f"systematic divergence: {{worst:.3e}}"
assert worst2 < 1e-12, f"spp=2 systematic divergence: {{worst2:.3e}}"

# deep temporal blocking (spp=4, halo=16) needs a taller grid for a
# legal 16-row tile; one quad pass vs four XLA steps
cfg4 = ShallowWaterConfig(nx=48, ny=64, dims=(1, 1), dtype=np.float64)
model4 = ShallowWaterModel(cfg4)
s4 = ModelState(
    *(jnp.asarray(b[0], jnp.float64) for b in model4.initial_state_blocks())
)
ref4 = model4.step(s4, first_step=True)
cur4 = fs.pad_state(cfg4, ref4, 16)
for _ in range(4):
    ref4 = model4.step(ref4)
cur4 = fs.fused_step(cfg4, cur4, block_rows=16, interpret=True,
                     steps_per_pass=4)
got4 = fs.crop_state(cfg4, cur4)
worst4 = 0.0
for a, b in zip(ref4, got4):
    d = float(jnp.max(jnp.abs(a - b)))
    worst4 = max(worst4, d / (1.0 + float(jnp.max(jnp.abs(a)))))
assert worst4 < 1e-12, f"spp=4 systematic divergence: {{worst4:.3e}}"
print(f"f64 worst scaled diff over 8 steps: {{worst:.3e}} "
      f"(spp=2: {{worst2:.3e}}, spp=4: {{worst4:.3e}})")
"""


def test_fused_matches_xla_step_f64_subprocess():
    """f64 equivalence: reordering-level agreement (~1e-13), the
    discriminating test a boundary bug cannot pass."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(_F64_SCRIPT.format(repo=REPO))],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "worst scaled diff" in proc.stdout


def test_verified_hot_loop_falls_back_on_cpu():
    """On the CPU platform the compiled Mosaic kernel cannot build, so
    the probe must decline cleanly (returns None, logs why) — this is
    the safety net bench.py and the example rely on."""
    cfg, model, state = _small_model()
    first = jax.jit(lambda s: model.step(s, first_step=True))
    lines = []
    from mpi4jax_tpu.models.fused_step import verified_hot_loop

    got = verified_hot_loop(
        cfg, model, 4, state, first, block_rows=8, log=lines.append
    )
    assert got is None
    # the probe may log per-candidate retry lines before the final
    # verdict, so the contract is over the whole log, not lines[0]
    assert lines and any(
        "unavailable" in ln or "too small" in ln for ln in lines
    ), lines
