"""Checkpoint/restore round-trip, incl. sharded arrays over the mesh
(superset subsystem; the reference has no checkpointing, SURVEY.md §5)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytest.importorskip("orbax.checkpoint")

from mpi4jax_tpu.utils import checkpoint  # noqa: E402


def test_roundtrip_plain(tmp_path):
    state = {
        "w": jnp.arange(12.0).reshape(3, 4),
        "step": jnp.asarray(7, jnp.int32),
        "nested": {"b": jnp.ones(5)},
    }
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    restored = checkpoint.restore(path, jax.tree.map(jnp.zeros_like, state))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_sharded(tmp_path, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("ranks"))
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
    path = os.path.join(tmp_path, "ckpt_sharded")
    checkpoint.save(path, {"x": x})
    restored = checkpoint.restore(path, {"x": x})
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert restored["x"].sharding == sharding


def test_restore_with_different_sharding(tmp_path, mesh):
    # resume on a different layout: saved row-sharded, restored
    # column-sharded — values identical, new NamedSharding honored
    from jax.sharding import NamedSharding, PartitionSpec as P

    row = NamedSharding(mesh, P("ranks", None))
    col = NamedSharding(mesh, P(None, "ranks"))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), row)
    path = os.path.join(tmp_path, "ckpt_reshard")
    checkpoint.save(path, {"x": x})
    template = {"x": jax.device_put(jnp.zeros((8, 8)), col)}
    restored = checkpoint.restore(path, template)
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert restored["x"].sharding == col


def test_restore_in_fresh_process(tmp_path, mesh):
    # real resume: a new process (fresh runtime, fresh mesh) restores
    # the sharded state and finds the same values on the same layout
    import subprocess
    import sys
    import textwrap

    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("ranks"))
    x = jax.device_put(jnp.arange(16.0).reshape(8, 2), sharding)
    path = os.path.join(tmp_path, "ckpt_resume")
    checkpoint.save(path, {"w": x, "step": jnp.asarray(3, jnp.int32)})

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {repo!r})
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np, jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mpi4jax_tpu.parallel import world_mesh
        from mpi4jax_tpu.utils import checkpoint
        mesh = world_mesh()
        sh = NamedSharding(mesh, P("ranks"))
        template = {{
            "w": jax.device_put(jnp.zeros((8, 2)), sh),
            "step": jnp.asarray(0, jnp.int32),
        }}
        restored = checkpoint.restore({path!r}, template)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(16.0).reshape(8, 2))
        assert int(restored["step"]) == 3
        assert restored["w"].sharding == sh
        assert len({{d.device for d in restored["w"].addressable_shards}}) == 8
        print("RESUME_OK")
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "RESUME_OK" in res.stdout
