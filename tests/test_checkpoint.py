"""Checkpoint/restore round-trip, incl. sharded arrays over the mesh
(superset subsystem; the reference has no checkpointing, SURVEY.md §5)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytest.importorskip("orbax.checkpoint")

from mpi4jax_tpu.utils import checkpoint  # noqa: E402


def test_roundtrip_plain(tmp_path):
    state = {
        "w": jnp.arange(12.0).reshape(3, 4),
        "step": jnp.asarray(7, jnp.int32),
        "nested": {"b": jnp.ones(5)},
    }
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    restored = checkpoint.restore(path, jax.tree.map(jnp.zeros_like, state))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_is_atomic_tmp_then_rename(tmp_path):
    """Saves stage into ``path + ".tmp"`` and rename into place: no
    tmp litter survives a successful save, stale litter from a killed
    previous save is swept, and an interrupted re-save can never
    corrupt the committed checkpoint it was replacing."""
    state = {"w": jnp.arange(6.0)}
    path = os.path.join(tmp_path, "ckpt")
    # stale litter from a "killed mid-save" predecessor
    os.makedirs(path + ".tmp")
    with open(os.path.join(path + ".tmp", "junk"), "w") as f:
        f.write("torn")
    checkpoint.save(path, state)
    assert not os.path.exists(path + ".tmp")
    restored = checkpoint.restore(path, {"w": jnp.zeros(6)})
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(6.0))
    # overwrite in place: the new value wins, still no litter
    checkpoint.save(path, {"w": jnp.ones(6)})
    assert not os.path.exists(path + ".tmp")
    restored = checkpoint.restore(path, {"w": jnp.zeros(6)})
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(6))


def test_tmp_litter_is_invisible_to_restore(tmp_path):
    """A committed checkpoint stays readable even while a failed
    re-save's ``.tmp`` staging dir sits beside it."""
    state = {"w": jnp.arange(4.0)}
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    os.makedirs(path + ".tmp")  # an in-flight (or dead) writer
    restored = checkpoint.restore(path, {"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(4.0))


def test_manager_latest_valid_skips_truncated_dir(tmp_path):
    """ISSUE-5 satellite: a deliberately truncated checkpoint
    directory (manifest gone) is skipped by
    ``CheckpointManager.latest_valid``/``restore_latest`` in favor of
    the older intact one."""
    from mpi4jax_tpu.resilience import CheckpointManager

    mgr = CheckpointManager(os.path.join(tmp_path, "root"), keep=4)
    mgr.save(1, {"w": jnp.full(3, 1.0)})
    mgr.save(2, {"w": jnp.full(3, 2.0)})
    newest = os.path.join(mgr.root, "step_00000002")
    os.unlink(os.path.join(newest, "manifest.json"))
    info = mgr.latest_valid()
    assert info is not None and info.step == 1
    step, restored = mgr.restore_latest({"w": jnp.zeros(3)})
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.full(3, 1.0)
    )


def test_roundtrip_sharded(tmp_path, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("ranks"))
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
    path = os.path.join(tmp_path, "ckpt_sharded")
    checkpoint.save(path, {"x": x})
    restored = checkpoint.restore(path, {"x": x})
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert restored["x"].sharding == sharding


def test_restore_with_different_sharding(tmp_path, mesh):
    # resume on a different layout: saved row-sharded, restored
    # column-sharded — values identical, new NamedSharding honored
    from jax.sharding import NamedSharding, PartitionSpec as P

    row = NamedSharding(mesh, P("ranks", None))
    col = NamedSharding(mesh, P(None, "ranks"))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), row)
    path = os.path.join(tmp_path, "ckpt_reshard")
    checkpoint.save(path, {"x": x})
    template = {"x": jax.device_put(jnp.zeros((8, 8)), col)}
    restored = checkpoint.restore(path, template)
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert restored["x"].sharding == col


def test_restore_in_fresh_process(tmp_path, mesh):
    # real resume: a new process (fresh runtime, fresh mesh) restores
    # the sharded state and finds the same values on the same layout
    import subprocess
    import sys
    import textwrap

    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("ranks"))
    x = jax.device_put(jnp.arange(16.0).reshape(8, 2), sharding)
    path = os.path.join(tmp_path, "ckpt_resume")
    checkpoint.save(path, {"w": x, "step": jnp.asarray(3, jnp.int32)})

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {repo!r})
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np, jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mpi4jax_tpu.parallel import world_mesh
        from mpi4jax_tpu.utils import checkpoint
        mesh = world_mesh()
        sh = NamedSharding(mesh, P("ranks"))
        template = {{
            "w": jax.device_put(jnp.zeros((8, 2)), sh),
            "step": jnp.asarray(0, jnp.int32),
        }}
        restored = checkpoint.restore({path!r}, template)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(16.0).reshape(8, 2))
        assert int(restored["step"]) == 3
        assert restored["w"].sharding == sh
        assert len({{d.device for d in restored["w"].addressable_shards}}) == 8
        print("RESUME_OK")
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "RESUME_OK" in res.stdout
