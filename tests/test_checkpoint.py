"""Checkpoint/restore round-trip, incl. sharded arrays over the mesh
(superset subsystem; the reference has no checkpointing, SURVEY.md §5)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytest.importorskip("orbax.checkpoint")

from mpi4jax_tpu.utils import checkpoint  # noqa: E402


def test_roundtrip_plain(tmp_path):
    state = {
        "w": jnp.arange(12.0).reshape(3, 4),
        "step": jnp.asarray(7, jnp.int32),
        "nested": {"b": jnp.ones(5)},
    }
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    restored = checkpoint.restore(path, jax.tree.map(jnp.zeros_like, state))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_sharded(tmp_path, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("ranks"))
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
    path = os.path.join(tmp_path, "ckpt_sharded")
    checkpoint.save(path, {"x": x})
    restored = checkpoint.restore(path, {"x": x})
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    assert restored["x"].sharding == sharding
