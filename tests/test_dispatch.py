"""Event-driven dispatch plane (PR 20): serving/dispatch.py.

Covers the ISSUE-20 acceptance surface:

- wake wires: selection/fallback matrix (inotify -> socket -> poll),
  socket round-trip with advertisement retraction, inotify
  rename-is-the-event, and lost-wakeup recovery via the retained
  bounded poll;
- batched lease claims: racing servers partition a batch exactly once
  (no job lost, none duplicated), and ``FairScheduler.pick_batch`` /
  ``commit_batch`` keep tenant round-robin across the batch boundary
  (with ``k=1`` exactly matching single ``pick``);
- job coalescing: fingerprint grouping (per-job state opts out),
  and a coalesced fastpath drain where every member keeps its own id,
  audits, gapless span chain, and terminal record while the runner
  executes fewer worlds than jobs;
- group commit: fsyncs-per-job < 2.0 at load (armed cp accounting and
  the dispatch snapshot agree), and a SIGKILL between fence and flush
  loses nothing — the interrupted-transition sweep requeues and the
  job still ends terminal exactly once;
- queue-wait decomposition: ``wake_latency`` joins the telescoping
  identity at >= 90% coverage on an armed fastpath drain;
- surfaces: ``dispatch --selftest`` + snapshot CLI, ``serve
  --fastpath`` round-trip with the ``status`` wire line,
  ``m4t_dispatch_*`` OpenMetrics families;
- chaos e2e (slow, ``-m 'dispatch and chaos'``): the PR 14 SIGKILL
  failover rerun with both servers on ``--fastpath`` — zero lost or
  duplicate ids.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from mpi4jax_tpu.observability import spans as ospans
from mpi4jax_tpu.serving import dispatch
from mpi4jax_tpu.serving import export as sexport
from mpi4jax_tpu.serving import profile
from mpi4jax_tpu.serving.scheduler import FairScheduler
from mpi4jax_tpu.serving.server import Server
from mpi4jax_tpu.serving.spool import Spool, parse_job

pytestmark = [pytest.mark.dispatch, pytest.mark.serving]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_env():
    env = dict(os.environ)
    env.setdefault("MPI4JAX_TPU_SKIP_VERSION_CHECK", "1")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _cli(*argv, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.serving", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=_cli_env(),
    )


def _submit_mix(spool, jobs, tenants=2, cmd=("-c", "pass")):
    for i in range(jobs):
        r = spool.submit({
            "id": f"j{i}", "tenant": f"t{i % tenants}",
            "cmd": list(cmd),
        })
        assert r["status"] == "queued", r


def _fast_drain(root, jobs=6, tenants=2, runner=None, **kw):
    """Submit + serve a stub mix through the event-driven loop."""
    spool = Spool(root)
    spool.configure(max(16, jobs))
    _submit_mix(spool, jobs, tenants)
    calls = []

    def default_runner(spec, world, events_dir, attempt, resume_step):
        calls.append(spec.id)
        return 0, []

    kw.setdefault("fastpath", "socket")
    server = Server(
        spool, nproc=1, max_jobs=jobs, poll_s=0.01,
        runner=runner or default_runner, log=lambda msg: None, **kw,
    )
    assert server.serve() == 0
    return spool, calls


@pytest.fixture
def armed(tmp_path, monkeypatch):
    monkeypatch.setenv(profile.ENV_VAR, "1")
    monkeypatch.setattr(sexport, "CP_SNAPSHOT_TTL_S", 0.0)
    profile.disarm()
    yield str(tmp_path / "spool")
    profile.disarm()


# ---------------------------------------------------------------------
# wake wires
# ---------------------------------------------------------------------


def test_wire_selection_and_fallback(tmp_path, monkeypatch):
    watch = str(tmp_path / "pending")
    # explicit poll: no events, bounded wait
    lst = dispatch.open_listener(watch, prefer="poll")
    assert lst.wire == dispatch.WIRE_POLL
    t0 = time.monotonic()
    assert lst.wait(0.01) == []
    assert time.monotonic() - t0 < 1.0
    lst.close()
    # explicit socket
    with dispatch.open_listener(watch, prefer="socket") as lst:
        assert lst.wire == dispatch.WIRE_SOCKET
    # inotify requested on a host without it: falls through the chain,
    # never raises
    monkeypatch.setattr(dispatch, "inotify_available", lambda: False)
    with dispatch.open_listener(watch, prefer="inotify") as lst:
        assert lst.wire == dispatch.WIRE_SOCKET
    # the default order picks the best available wire
    with dispatch.open_listener(watch) as lst:
        assert lst.wire in (dispatch.WIRE_SOCKET, dispatch.WIRE_POLL)


def test_socket_wire_round_trip(tmp_path):
    root = str(tmp_path)
    watch = os.path.join(root, "pending")
    lst = dispatch.open_listener(watch, advertise_dir=root,
                                 prefer="socket")
    try:
        ad = os.path.join(root, dispatch.WAKE_NAME)
        assert os.path.exists(ad)
        with open(ad) as f:
            rec = json.load(f)
        assert rec["port"] == lst.port
        t_sent = time.time()
        assert dispatch.notify(root, job="jx") is True
        (ev,) = lst.wait(5.0)
        assert ev["job"] == "jx" and ev["wire"] == dispatch.WIRE_SOCKET
        # the datagram carries the submit stamp: wake latency is
        # attributable at the listener
        assert abs(float(ev["t"]) - t_sent) < 5.0
    finally:
        lst.close()
    # close retracts the advertisement; notify degrades to a no-op
    assert not os.path.exists(os.path.join(root, dispatch.WAKE_NAME))
    assert dispatch.notify(root, job="jy") is False


@pytest.mark.skipif(not dispatch.inotify_available(),
                    reason="inotify unavailable on this host")
def test_inotify_wire_rename_is_the_event(tmp_path):
    watch = str(tmp_path / "pending")
    with dispatch.open_listener(watch, prefer="inotify") as lst:
        assert lst.wire == dispatch.WIRE_INOTIFY
        stamp = time.time_ns()
        name = f"{stamp:020d}-jz.json"
        tmp = os.path.join(watch, f".tmp-{name}")
        with open(tmp, "w") as f:
            f.write("{}")
        os.replace(tmp, os.path.join(watch, name))
        evs = lst.wait(5.0)
        (ev,) = [e for e in evs if e.get("job") == "jz"]
        # the entry-name time_ns prefix is recovered as the wake stamp
        assert ev["t"] == pytest.approx(stamp / 1e9)
        # the tmp write itself was filtered, not reported
        assert not any(
            e.get("name", "").startswith(".tmp-") for e in evs
        )


def test_lost_wakeup_recovery(tmp_path, monkeypatch):
    """Every datagram dropped: the retained bounded poll still finds
    the work within a poll interval — wake delivery is advisory,
    never correctness."""
    monkeypatch.setattr(dispatch, "notify",
                        lambda root, job=None: False)
    spool = Spool(str(tmp_path / "sp"))
    server = Server(
        spool, nproc=1, max_jobs=1, poll_s=0.02, fastpath="socket",
        runner=lambda *a: (0, []), log=lambda msg: None,
    )
    t = threading.Thread(target=server.serve)
    t.start()
    try:
        time.sleep(0.1)  # the loop is idle-waiting on the wire
        assert spool.submit({"id": "lost", "cmd": ["-c", "pass"]})[
            "status"] == "queued"
        t.join(30)
        assert not t.is_alive()
    finally:
        t.join(5)
    (rec,) = spool.done()
    assert rec["id"] == "lost" and rec["outcome"] == "completed"


def test_submit_notifies_the_serve_loop(tmp_path):
    """The wake path end to end: a submit's datagram lands on the
    spool listener without any server in the loop."""
    spool = Spool(str(tmp_path / "sp"))
    lst = dispatch.open_listener(
        os.path.join(spool.root, "pending"),
        advertise_dir=spool.root, prefer="socket",
    )
    try:
        assert spool.submit({"id": "w0", "cmd": ["-c", "pass"]})[
            "status"] == "queued"
        evs = lst.wait(5.0)
        assert any(e.get("job") == "w0" for e in evs), evs
    finally:
        lst.close()


# ---------------------------------------------------------------------
# batched lease claims
# ---------------------------------------------------------------------


def test_claim_batch_exactly_once_under_racing_servers(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    spool.configure(64)
    for i in range(12):
        assert spool.submit({"id": f"b{i}", "cmd": ["-c", "pass"]})[
            "status"] == "queued"
    wins = {}
    barrier = threading.Barrier(4)

    def racer(sid):
        mine = spool.pending()
        barrier.wait()
        wins[sid] = [s.id for s in spool.claim_batch(mine, server=sid)]

    threads = [threading.Thread(target=racer, args=(f"s{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    claimed = [j for ids in wins.values() for j in ids]
    # partitioned: every id leased exactly once across the fleet
    assert sorted(claimed) == sorted(f"b{i}" for i in range(12)), wins
    assert spool.pending() == []
    # every winner knows its owner and epoch (the PR 14 fence inputs)
    for sid, ids in wins.items():
        for rec in spool.audit_records():
            if rec["event"] == "claimed" and rec["job"] in ids:
                assert rec["server"] in wins


def test_claim_batch_int_form_is_fifo(tmp_path):
    spool = Spool(str(tmp_path / "sp"))
    for i in range(5):
        assert spool.submit({"id": f"k{i}", "cmd": ["-c", "pass"]})[
            "status"] == "queued"
    won = spool.claim_batch(3, server="s1")
    assert [s.id for s in won] == ["k0", "k1", "k2"]
    assert [s.id for s in spool.pending()] == ["k3", "k4"]


def test_pick_batch_fairness_across_the_boundary(tmp_path):
    sched = FairScheduler()
    mix = [parse_job({"id": f"f{i}", "tenant": t,
                      "cmd": ["-c", "pass"]})
           for i, t in enumerate(["a", "a", "a", "b", "c"])]
    picked = sched.pick_batch(mix, 3)
    # round-robin across tenants inside the batch, not 3x tenant a
    assert [s.id for s in picked] == ["f0", "f3", "f4"]
    sched.commit_batch(picked)
    rest = [s for s in mix if s not in picked]
    assert [s.id for s in sched.pick_batch(rest, 3)] == ["f1", "f2"]


def test_pick_batch_k1_matches_single_pick():
    mix = [parse_job({"id": f"p{i}", "tenant": t,
                      "cmd": ["-c", "pass"]})
           for i, t in enumerate(["a", "b", "a", "c", "b", "a"])]
    one, batch = FairScheduler(), FairScheduler()
    singles, batched = [], []
    p1, p2 = list(mix), list(mix)
    while p1:
        s = one.pick(p1)
        singles.append(s.id)
        p1.remove(s)
        (t,) = batch.pick_batch(p2, 1)
        batch.commit_batch([t])
        batched.append(t.id)
        p2.remove(t)
    assert batched == singles


def test_pick_batch_losers_burn_no_turn():
    """A server that loses part of its picked batch to a peer commits
    only the winners: the losing tenants' turns are intact."""
    sched = FairScheduler()
    mix = [parse_job({"id": f"r{i}", "tenant": t,
                      "cmd": ["-c", "pass"]})
           for i, t in enumerate(["a", "b"])]
    picked = sched.pick_batch(mix, 2)
    assert [s.id for s in picked] == ["r0", "r1"]
    # the peer took r1: only r0 committed — tenant b never served
    sched.commit_batch([picked[0]])
    nxt = sched.pick_batch([mix[1]], 1)
    assert [s.id for s in nxt] == ["r1"]


# ---------------------------------------------------------------------
# job coalescing
# ---------------------------------------------------------------------


def test_coalesce_groups_by_execution_fingerprint():
    same = [parse_job({"id": f"c{i}", "cmd": ["-c", "pass"]})
            for i in range(3)]
    odd = parse_job({"id": "odd", "cmd": ["-c", "print(1)"]})
    wide = parse_job({"id": "wide", "cmd": ["-c", "pass"], "nproc": 2})
    groups = dispatch.coalesce([same[0], odd, same[1], wide, same[2]])
    assert [[s.id for s in g] for g in groups] == [
        ["c0", "c1", "c2"], ["odd"], ["wide"],
    ]


def test_per_job_state_never_coalesces(tmp_path):
    base = {"cmd": ["-c", "pass"]}
    resumes = parse_job(dict(base, id="r",
                             resume_dir=str(tmp_path / "ck")))
    faulty = parse_job(dict(base, id="f", fault_plan={"faults": [
        {"rank": 0, "op": "AllReduce", "nth": 1, "action": "wedge"},
    ]}))
    gated = parse_job(dict(base, id="v", verify=True))
    plain = parse_job(dict(base, id="p"))
    for spec in (resumes, faulty, gated):
        assert dispatch.coalesce_key(spec) is None
    assert dispatch.coalesce_key(plain) is not None
    groups = dispatch.coalesce([resumes, plain, faulty, gated])
    assert [[s.id for s in g] for g in groups] == [
        ["r"], ["p"], ["f"], ["v"],
    ]


def test_coalesced_drain_keeps_per_job_accounting(tmp_path):
    """Six same-shape jobs + the shared fastpath loop: fewer worlds
    than jobs execute, yet every id keeps its own terminal record,
    audits, and a gapless span chain."""
    spool, calls = _fast_drain(str(tmp_path / "sp"), jobs=6, batch=6)
    assert 0 < len(calls) < 6, calls
    done = {r["id"]: r for r in spool.done()}
    assert sorted(done) == [f"j{i}" for i in range(6)]
    assert all(r["outcome"] == "completed" for r in done.values())
    # one terminal audit per id, exactly once
    for i in range(6):
        terms = [r for r in spool.audit_records()
                 if r["event"] in ("completed", "failed", "rejected")
                 and r.get("job") == f"j{i}"]
        assert len(terms) == 1, (i, terms)
    # gapless chains for every member (the boundary reads are shared)
    verdicts = ospans.verify_chains(
        spool.span_records(), jobs=[f"j{i}" for i in range(6)],
    )
    for job, v in verdicts.items():
        assert v["complete"], (job, v)
    # members that shared a dispatch say so (additive fields)
    coalesced = [s for s in spool.span_records()
                 if s.get("coalesced") and s.get("span") == "dispatch"]
    assert coalesced
    assert all(s.get("leader") for s in coalesced)


def test_no_coalesce_runs_every_job_alone(tmp_path):
    spool, calls = _fast_drain(
        str(tmp_path / "sp"), jobs=4, coalesce=False, batch=4,
    )
    assert len(calls) == 4
    snap = dispatch.load_snapshot(spool.root)
    assert snap["coalesced_jobs"] == 0


def test_coalesced_failure_fails_every_member(tmp_path):
    spool, _ = _fast_drain(
        str(tmp_path / "sp"), jobs=3, batch=3, tenants=1,
        runner=lambda *a: (1, []),
    )
    done = {r["id"]: r for r in spool.done()}
    assert sorted(done) == ["j0", "j1", "j2"]
    assert all(r["outcome"] == "failed" for r in done.values())
    for rec in done.values():
        assert rec["exit_code"] == 1


# ---------------------------------------------------------------------
# group commit
# ---------------------------------------------------------------------


def test_group_commit_fsyncs_per_job_below_two(armed):
    spool, _ = _fast_drain(armed, jobs=8, batch=8)
    recs = profile.load_cp(spool.root)
    fsyncs = sum(
        int(r.get("n", 1)) for r in recs
        if r.get("phase") in ("submit.fsync", "finish.fsync")
    )
    jobs = len(spool.done())
    assert jobs == 8
    assert fsyncs / jobs < 2.0, (fsyncs, jobs)
    # the batch flushed through the journal: one commit point, and
    # every record is in it
    commits = [r for r in recs if r.get("phase") == "finish.fsync"]
    assert sum(int(c.get("jobs", 0)) for c in commits) == 8
    with open(os.path.join(spool.root, "commit.jsonl")) as f:
        journal = [json.loads(line) for line in f if line.strip()]
    assert sorted(r["id"] for r in journal) == sorted(
        r["id"] for r in spool.done()
    )
    snap = dispatch.load_snapshot(spool.root)
    assert snap["fsyncs_per_job"] is not None
    assert snap["fsyncs_per_job"] < 2.0


def test_group_commit_sigkill_between_fence_and_flush(tmp_path):
    """The crash window group commit opens: a server dies after the
    atomic fence but before the journal flush. The tombstone survives,
    the sweep requeues, a healthy server re-runs — one terminal record,
    exactly once."""
    sp = str(tmp_path / "sp")
    spool = Spool(sp)
    assert spool.submit({"id": "gc", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    script = textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {REPO!r})
        from mpi4jax_tpu.serving.spool import Spool
        spool = Spool({sp!r})
        (spec,) = spool.pending()
        got = spool.claim(spec, server="crash-s1")
        assert got is not None
        token = spool.fence(got, "completed", server="crash-s1")
        assert token and os.path.exists(token)
        os.kill(os.getpid(), signal.SIGKILL)  # dies holding the take
    """)
    proc = subprocess.run(
        [sys.executable, "-c", script], env=_cli_env(), timeout=120,
        capture_output=True, text=True,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # neither terminal nor pending — the fenced-but-unflushed state
    assert spool.done() == [] and spool.pending() == []
    # the scavenger resolves the interrupted transition
    actions = spool.reclaim(by="sweeper")
    assert any(a.get("reason") == "interrupted_transition"
               and a.get("action") == "requeued" for a in actions), (
        actions
    )
    (spec,) = spool.pending()
    assert spec.id == "gc" and spec.reclaims == 1
    # a healthy fastpath server completes it — terminal exactly once
    server = Server(
        spool, nproc=1, max_jobs=1, poll_s=0.01, fastpath="socket",
        server_id="s2", runner=lambda *a: (0, []),
        log=lambda msg: None,
    )
    assert server.serve() == 0
    (rec,) = spool.done()
    assert rec["id"] == "gc" and rec["outcome"] == "completed"
    terms = [r for r in spool.audit_records()
             if r["event"] in ("completed", "failed", "rejected")]
    assert len(terms) == 1


def test_buffered_fence_rejects_zombies_eagerly(tmp_path):
    """A superseded epoch is fenced at fence() time, before any group
    commit — the zombie's record never reaches the journal."""
    spool = Spool(str(tmp_path / "sp"))
    assert spool.submit({"id": "z", "cmd": ["-c", "pass"]})[
        "status"] == "queued"
    (spec,) = spool.pending()
    got = spool.claim(spec, server="s1")
    # s1 goes silent; the scavenger hands the job to s2 (epoch 2)
    spool.reclaim(by="s2", now=time.time() + 3600)
    (spec2,) = spool.pending()
    got2 = spool.claim(spec2, server="s2")
    assert got2.epoch == 2
    # the zombie's fence fails and audits; s2's fence succeeds
    assert spool.fence(got, "completed", server="s1") is None
    fenced = [r for r in spool.audit_records()
              if r["event"] == "fenced"]
    assert fenced and fenced[-1]["server"] == "s1"
    token = spool.fence(got2, "completed", server="s2")
    assert token
    landed = spool.finish_batch([{
        "spec": got2, "outcome": "completed", "extra": {},
        "token": token,
    }])
    assert landed == 1
    (rec,) = spool.done()
    assert rec["outcome"] == "completed"


# ---------------------------------------------------------------------
# queue-wait decomposition: wake_latency
# ---------------------------------------------------------------------


def test_wake_latency_in_decomposition(armed):
    """Armed fastpath drain: the six queue phases (wake_latency
    included) telescope to the queued span at >= 90% coverage."""
    spool, _ = _fast_drain(armed, jobs=6, tenants=3, batch=3)
    decomps = profile.decompose(spool.root)
    assert len(decomps) == 6
    for d in decomps:
        assert d["ok"], d
        assert set(d["phases"]) == set(profile.QUEUE_PHASES)
        assert "wake_latency" in d["phases"]
        assert abs(d["sum_s"] - d["queue_wait_s"]) <= (
            profile.SUM_TOLERANCE_S
        ), d
        assert d["coverage"] >= 0.90, d
        assert all(v >= 0 for v in d["phases"].values()), d


def test_wake_latency_phase_is_in_the_vocabulary():
    assert "wake_latency" in profile.PHASES
    assert "claim_batch" in profile.PHASES
    assert "wake_latency" in profile.QUEUE_PHASES


# ---------------------------------------------------------------------
# surfaces: snapshot, exporter, status, CLI
# ---------------------------------------------------------------------


def test_dispatch_snapshot_shape(tmp_path):
    spool, _ = _fast_drain(str(tmp_path / "sp"), jobs=4, batch=4)
    snap = dispatch.load_snapshot(spool.root)
    assert snap["schema"] == dispatch.DISPATCH_SCHEMA
    assert snap["wire"] == dispatch.WIRE_SOCKET
    assert snap["jobs"] == 4
    assert snap["batches"] >= 1
    assert snap["batch_size_max"] <= 4
    assert snap["group_commits"] >= 1
    # a spool never served event-driven has no snapshot
    assert dispatch.load_snapshot(str(tmp_path / "empty")) is None


def test_exporter_dispatch_families(tmp_path):
    spool, _ = _fast_drain(str(tmp_path / "sp"), jobs=4, batch=4)
    snap = sexport.serving_snapshot(spool)
    assert snap["dispatch"]["jobs"] == 4
    text = sexport.render_serving_metrics(snap)
    assert 'm4t_dispatch_wire{wire="socket"} 1' in text
    assert "m4t_dispatch_batches_total" in text
    assert 'm4t_dispatch_batch_size{quantile="0.5"}' in text
    assert "m4t_dispatch_coalesced_jobs_total" in text
    assert "m4t_dispatch_group_commits_total" in text
    assert "m4t_dispatch_fsyncs_per_job" in text
    assert text.rstrip().endswith("# EOF")


def test_classic_drain_exports_no_dispatch_families(tmp_path):
    """The families are fastpath-only: a classic drain's exposition is
    unchanged."""
    spool = Spool(str(tmp_path / "sp"))
    _submit_mix(spool, 2)
    server = Server(spool, nproc=1, max_jobs=2, poll_s=0.01,
                    runner=lambda *a: (0, []), log=lambda msg: None)
    assert server.serve() == 0
    text = sexport.render_serving_metrics(
        sexport.serving_snapshot(spool)
    )
    assert "m4t_dispatch_" not in text


def test_dispatch_selftest_cli():
    r = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.serving", "dispatch",
         "--selftest"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env=_cli_env(),
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "dispatch selftest ok" in r.stdout


def test_fastpath_cli_round_trip(tmp_path):
    """serve --fastpath over real subprocess jobs, then the dispatch
    and status surfaces name the wire and the counters."""
    sp = str(tmp_path / "sp")
    for i in range(2):
        r = _cli("submit", sp, "--id", f"cli{i}", "--", "-c", "pass")
        assert r.returncode == 0, (r.stdout, r.stderr)
    r = _cli("serve", sp, "-n", "1", "--fastpath", "socket",
             "--batch", "4", "--max-jobs", "2", "--poll", "0.05")
    assert r.returncode == 0, (r.stdout, r.stderr)
    spool = Spool(sp)
    done = {rec["id"]: rec for rec in spool.done()}
    assert sorted(done) == ["cli0", "cli1"]
    assert all(rec["outcome"] == "completed"
               for rec in done.values())
    r = _cli("dispatch", sp)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "wire: socket" in r.stdout
    r = _cli("dispatch", sp, "--json")
    snap = json.loads(r.stdout)
    assert snap["jobs"] == 2
    r = _cli("status", sp)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "dispatch: wire socket" in r.stdout
    # a spool with no event-driven history: explicit rc 2
    r = _cli("dispatch", str(tmp_path / "never"))
    assert r.returncode == 2


# ---------------------------------------------------------------------
# chaos e2e: federation failover on the fastpath
# ---------------------------------------------------------------------


def _wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.federation
def test_chaos_sigkill_failover_on_fastpath(tmp_path):
    """The ISSUE-14 chaos drill rerun with both servers event-driven:
    the owner is SIGKILLed mid-job, the survivor's scavenger reclaims,
    and every submitted id ends terminal exactly once — wake wires,
    batched claims and group commit change no federation invariant."""
    sp = str(tmp_path / "sp")
    spool = Spool(sp)
    job = textwrap.dedent("""
        import sys, time
        time.sleep(float(sys.argv[1]))
    """)
    script = str(tmp_path / "napper.py")
    with open(script, "w") as f:
        f.write(job)
    assert spool.submit({
        "id": "orph", "cmd": [script, "30"], "timeout_s": 120.0,
    })["status"] == "queued"
    assert spool.submit({
        "id": "quick", "cmd": [script, "0"], "timeout_s": 60.0,
    })["status"] == "queued"

    def serve(server_id, log_path):
        return subprocess.Popen(
            [sys.executable, "-m", "mpi4jax_tpu.serving", "serve", sp,
             "-n", "1", "--poll", "0.05", "--server-id", server_id,
             "--lease", "0.5", "--fastpath", "socket", "--batch", "4"],
            cwd=REPO, env=_cli_env(), start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=open(log_path, "w"),
        )

    p1 = serve("fp-s1", str(tmp_path / "s1.log"))
    p2 = None
    try:
        _wait_for(
            lambda: any(r["event"] == "claimed"
                        and r.get("job") == "orph"
                        and r.get("server") == "fp-s1"
                        for r in spool.audit_records()),
            60, "fp-s1 to claim the long job",
        )
        os.killpg(os.getpgid(p1.pid), signal.SIGKILL)
        p1.wait(30)
        p2 = serve("fp-s2", str(tmp_path / "s2.log"))
        _wait_for(
            lambda: {r["id"] for r in spool.done()} >= {"orph",
                                                        "quick"},
            120, "the survivor to reclaim and finish both jobs",
        )
    finally:
        for p in (p1, p2):
            if p is not None and p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except OSError:
                    pass
    _cli("drain", sp)
    if p2 is not None:
        p2.wait(120)
    # zero lost, zero duplicated: every id terminal exactly once
    done = [r["id"] for r in spool.done()]
    assert sorted(done) == ["orph", "quick"]
    for job_id in ("orph", "quick"):
        terms = [r for r in spool.audit_records()
                 if r["event"] in ("completed", "failed", "rejected")
                 and r.get("job") == job_id]
        assert len(terms) == 1, (job_id, terms)
    # the orphan failed over: reclaimed by the survivor
    (orph,) = [r for r in spool.done() if r["id"] == "orph"]
    assert orph["reclaims"] == 1
    assert orph["reclaimed_from"][0]["server"] == "fp-s1"
    snap = dispatch.load_snapshot(sp)
    assert snap is not None and snap["wire"] == dispatch.WIRE_SOCKET
