"""Programmable collective algorithms (planner/algo.py +
analysis/algo_check.py): the m4t-algo/1 DSL, the simulator-backed
admission pipeline (M4T201/202 via simulate.py, M4T204 chunk coverage,
M4T205 cost admission), proof artifacts, the registry, fingerprint
drift pins against the recorder/plan schemas, property-based agreement
with brute-force reference implementations, the committed negative
fixtures, the CLI, and ``launch --verify --algo`` as a pre-spawn gate.

All device-free. Regenerate the golden after an intentional change to
a shipped algorithm or the lowering::

    python tests/test_planner_algo.py --regen
"""

import copy
import json
import os
import random
import shutil
import subprocess
import sys
from collections import Counter

import pytest

from mpi4jax_tpu.analysis import algo_check
from mpi4jax_tpu.analysis.schedule import ScheduleEvent
from mpi4jax_tpu.analysis.simulate import simulate_events, simulate_rounds
from mpi4jax_tpu.observability import costmodel, recorder
from mpi4jax_tpu.planner import algo as algomod
from mpi4jax_tpu.planner import autotune, plan as planmod

pytestmark = [pytest.mark.tuning, pytest.mark.algo]

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GOLDEN = os.path.join(HERE, "data", "algo_golden.json")
DEADLOCK_FIXTURE = os.path.join(HERE, "data", "algo_deadlock.json")
BADCOV_FIXTURE = os.path.join(HERE, "data", "algo_badcoverage.json")

SHIPPED = ("ring", "recursive_double", "alltoall_twophase")
WORLDS = (2, 4, 8)


def shipped_path(stem):
    return os.path.join(algomod.algos_dir(), stem + ".json")


@pytest.fixture(autouse=True)
def _fresh_registry():
    """The registry caches on (path, mtime); tests that sideload via
    M4T_ALGO_PATH must not leak entries into each other."""
    algomod.invalidate_cache()
    yield
    algomod.invalidate_cache()


# ---------------------------------------------------------------------
# fingerprint drift pins: the compiler's event identity is the
# recorder's, byte for byte
# ---------------------------------------------------------------------


def test_event_fingerprint_literal_pin():
    """Drift pin: the exact strings the simulator matches on. If this
    breaks, every committed proof artifact is stale — regenerate them
    (`planner algo check --write-proof`) and say why in the commit."""
    assert algomod.event_fingerprint(1) == "Sendrecv[1x1:float32]@ranks"
    assert algomod.event_fingerprint(2) == "Sendrecv[2x1:float32]@ranks"


def test_event_fingerprint_matches_recorder_schema():
    for count in (1, 2, 7):
        assert algomod.event_fingerprint(count) == recorder.fingerprint({
            "op": "Sendrecv",
            "shape": (count, 1),
            "dtype": "float32",
            "axes": ("ranks",),
        })


def test_events_carry_recorder_fingerprints():
    spec = algomod.load(shipped_path("ring"))
    program = algomod.expand(spec, 4)
    for r, evs in algomod.events_for(program).items():
        for e in evs:
            assert e.fingerprint == recorder.fingerprint({
                "op": e.op, "shape": (e.nbytes // 4, 1),
                "dtype": e.dtype, "axes": ("ranks",),
            })


def test_algo_impl_tag_roundtrips_through_plan_cache(tmp_path):
    """An ``algo:<name>@<fp>`` impl tag survives plan save/load and is
    addressed by the same ``key_from_record`` key the telemetry join
    uses — the end-to-end contract `planner tune` relies on."""
    tag = algomod.load(shipped_path("ring")).tag
    key = planmod.plan_key(
        "AllReduce", nbytes=4096, dtype="float32", world=8,
        axes=("ranks",), platform="cpu",
    )
    p = planmod.Plan(platform="cpu")
    p.entries[key] = planmod.PlanEntry(impl=tag, source="analytic")
    path = str(tmp_path / "plan.json")
    planmod.save(p, path)
    loaded = planmod.load(path)
    assert loaded.entries[key].impl == tag
    record = {"op": "AllReduce", "bytes": 4096, "dtype": "float32",
              "world": 8, "axes": ["ranks"]}
    assert planmod.key_from_record(record, "cpu") == key


# ---------------------------------------------------------------------
# shipped algorithms: proven, proof-fresh, registered
# ---------------------------------------------------------------------


@pytest.mark.parametrize("stem", SHIPPED)
def test_shipped_algorithm_proves_clean_at_all_worlds(stem):
    reports = algo_check.check_file(shipped_path(stem), WORLDS)
    assert len(reports) == len(WORLDS)
    for rep in reports:
        assert rep.verdict == "deadlock-free", rep.to_text()
        assert rep.cost is not None and "algo" in rep.cost
    assert algo_check.reports_clean(reports)


@pytest.mark.parametrize("stem", SHIPPED)
def test_shipped_proof_artifact_is_fresh(stem):
    path = shipped_path(stem)
    spec = algomod.load(path)
    with open(algomod.proof_path(path)) as f:
        proof = json.load(f)
    assert algo_check.proof_mismatch(spec, proof) is None
    assert proof["fingerprint"] == spec.fingerprint
    assert sorted(int(w) for w in proof["worlds"]) == list(WORLDS)


def test_registry_contains_all_shipped_algorithms():
    assert algomod.assert_all_registered() >= 3
    reg = algomod.registry()
    tags = {impl.spec.name: tag for tag, impl in reg.items()}
    assert {"ring", "recursive-double", "alltoall-twophase"} <= set(tags)
    ar = algomod.impl_tags_for("AllReduce")
    assert tags["ring"] in ar and tags["recursive-double"] in ar
    assert tags["alltoall-twophase"] in algomod.impl_tags_for("AllToAll")
    for tag, impl in reg.items():
        assert tag == f"algo:{impl.spec.name}@{impl.spec.fingerprint}"
        assert tag in planmod.impls_for(impl.op)


def test_unproven_file_is_rejected_not_registered(tmp_path, monkeypatch):
    """No proof artifact -> the file cannot register (and
    assert_all_registered, the CI gate, raises)."""
    shutil.copy(shipped_path("ring"), tmp_path / "ring.json")
    monkeypatch.setenv("M4T_ALGO_PATH", str(tmp_path))
    algomod.invalidate_cache()
    reg = algomod.registry(refresh=True)
    assert all(i.path != str(tmp_path / "ring.json") for i in reg.values())
    rejects = dict(algomod.registry_rejects())
    assert str(tmp_path / "ring.json") in rejects
    assert "proof" in rejects[str(tmp_path / "ring.json")]
    # a sideloaded reject does not break the shipped-file CI gate...
    assert algomod.assert_all_registered() >= 3
    # ...but an unproven file in the shipped directory does
    monkeypatch.setattr(algomod, "algos_dir", lambda: str(tmp_path))
    algomod.invalidate_cache()
    with pytest.raises(SystemExit):
        algomod.assert_all_registered()


def test_stale_proof_is_rejected_after_edit(tmp_path, monkeypatch):
    """Fingerprint drift pin: editing the algorithm body invalidates
    the committed proof — the registry must refuse, not trust."""
    src = shipped_path("ring")
    dst = str(tmp_path / "ring_copy.json")
    with open(src) as f:
        raw = json.load(f)
    raw["name"] = "ring-copy"  # distinct tag from the shipped ring
    with open(dst, "w") as f:
        json.dump(raw, f)
    spec = algomod.load(dst)
    reports = algo_check.check_spec(spec, WORLDS)
    algo_check.write_proof(spec, reports)
    monkeypatch.setenv("M4T_ALGO_PATH", str(tmp_path))
    algomod.invalidate_cache()
    assert any(i.path == dst for i in algomod.registry(refresh=True).values())
    with open(dst) as f:
        raw = json.load(f)
    raw["worlds"] = [2, 4]  # any body edit moves the fingerprint
    with open(dst, "w") as f:
        json.dump(raw, f)
    algomod.invalidate_cache()
    assert all(i.path != dst for i in algomod.registry(refresh=True).values())
    rejects = dict(algomod.registry_rejects())
    assert "stale proof" in rejects[dst]


# ---------------------------------------------------------------------
# negative fixtures: the committed counterexamples
# ---------------------------------------------------------------------


def test_deadlock_fixture_yields_rank_cycle_witness():
    reports = algo_check.check_file(DEADLOCK_FIXTURE, [4])
    (rep,) = reports
    assert not rep.deadlock_free
    finding = next(f for f in rep.findings if f.code == "M4T201")
    assert "rank cycle" in finding.message
    assert "0 -> 1 -> 2 -> 3 -> 0" in finding.message


def test_badcoverage_fixture_names_missing_chunk():
    reports = algo_check.check_file(BADCOV_FIXTURE, [4])
    (rep,) = reports
    assert not rep.deadlock_free
    codes = {f.code for f in rep.findings}
    assert codes == {"M4T204"}
    msgs = " ".join(f.message for f in rep.findings)
    assert "chunk coverage violation" in msgs
    assert "missing contribution" in msgs
    witness = rep.findings[0].witness
    assert {"rank", "chunk", "missing"} <= set(witness)


def test_cost_admission_rejects_broken_expect_bounds():
    with open(shipped_path("ring")) as f:
        raw = json.load(f)
    raw["expect"] = {"rounds": "n - 1", "wire_chunks": "2 * (n - 1)"}
    spec = algomod.parse(raw)
    (rep,) = algo_check.check_spec(spec, [4])
    assert not rep.deadlock_free
    assert {f.code for f in rep.findings} == {"M4T205"}
    assert "rounds" in rep.findings[0].message


def test_over_reduction_is_named():
    """Reducing the same contribution twice is an M4T204, not silent
    numerical corruption: an exchange-and-reduce run for one round too
    many applies every contribution 2x."""
    spec = algomod.parse({
        "schema": algomod.SCHEMA, "name": "double-reduce",
        "collective": "AllReduce", "reduce": "SUM",
        "worlds": [2], "chunks": 1,
        "phases": [{"repeat": 2, "steps": [
            {"to": "r ^ 1", "from": "r ^ 1",
             "send": 0, "recv": 0, "action": "reduce"},
        ]}],
    })
    (rep,) = algo_check.check_spec(spec, [2])
    msgs = " ".join(
        f.message for f in rep.findings if f.code == "M4T204"
    )
    assert "over-reduced" in msgs and "applied 2x" in msgs


# ---------------------------------------------------------------------
# property tests: the admission pipeline vs brute force
# ---------------------------------------------------------------------


def _p2p_event(rank, to, frm, world):
    edges = []
    sends = recvs = ()
    if to >= 0:
        edges.append((rank, to))
        sends = (to,)
    if frm >= 0:
        edges.append((frm, rank))
        recvs = (frm,)
    return ScheduleEvent(
        op="Sendrecv", fingerprint=algomod.event_fingerprint(1),
        kind="p2p", group=tuple(sorted({rank} | set(sends) | set(recvs))),
        edges=tuple(edges), sends=sends, recvs=recvs,
        nbytes=4, dtype="float32", world=world,
    )


def _brute_force_blocking(events):
    """Reference rendezvous matcher, written independently of
    simulate.py: every round, an event completes iff each of its send
    peers currently receives from this rank and each recv peer
    currently sends to it; no progress with work left is a deadlock."""
    world = len(events)
    pcs = [0] * world
    while True:
        if all(pcs[r] >= len(events[r]) for r in range(world)):
            return True

        def matched(r):
            if pcs[r] >= len(events[r]):
                return False
            e = events[r][pcs[r]]
            for d in e.sends:
                if pcs[d] >= len(events[d]):
                    return False
                if r not in events[d][pcs[d]].recvs:
                    return False
            for s in e.recvs:
                if pcs[s] >= len(events[s]):
                    return False
                if r not in events[s][pcs[s]].sends:
                    return False
            return True

        done = [r for r in range(world) if matched(r)]
        if not done:
            return False
        for r in done:
            pcs[r] += 1


def test_simulator_agrees_with_brute_force_blocking_matcher():
    """1000 random synthetic p2p schedules: the production simulator
    and the independent reference matcher must agree on every verdict
    (and on completability — a clean verdict really drains every pc)."""
    rng = random.Random(0xA160)
    agree_clean = agree_deadlock = 0
    for seed in range(1000):
        rng.seed(seed)
        world = rng.choice((2, 3, 4))
        events = {r: [] for r in range(world)}
        for _step in range(rng.randint(1, 3)):
            if rng.random() < 0.55:
                # symmetric shifted exchange: always completable, so
                # the family exercises clean verdicts too
                k = rng.randrange(1, world)
                for r in range(world):
                    events[r].append(_p2p_event(
                        r, (r + k) % world, (r - k) % world, world,
                    ))
            else:
                for r in range(world):
                    peers = [p for p in range(world) if p != r]
                    to = rng.choice([-1] + peers)
                    frm = rng.choice([-1] + peers)
                    if to == -1 and frm == -1:
                        to = rng.choice(peers)
                    events[r].append(_p2p_event(r, to, frm, world))
        ok_sim, _rounds, findings = simulate_events(events)
        ok_ref = _brute_force_blocking(events)
        assert ok_sim == ok_ref, (
            f"seed {seed}: simulator={ok_sim} brute-force={ok_ref}"
        )
        if ok_sim:
            agree_clean += 1
        else:
            agree_deadlock += 1
            assert any(f.code == "M4T201" for f in findings)
    # the family must actually exercise both verdicts
    assert agree_clean > 100 and agree_deadlock > 100


def _brute_force_values(program, reduce_name="SUM"):
    """Independent concrete-value interpreter: run the program over
    numpy-free python ints where rank r's chunk c starts as the basis
    value (r, c), with snapshot-at-send semantics, driven by the same
    matched-round order as _brute_force_blocking."""
    n, S = program.world, program.slots
    state = {r: [None] * S for r in range(n)}
    for r in range(n):
        for c in range(program.chunks):
            state[r][c] = Counter({(r, c): 1})
    for r in range(n):
        state[r] = [v if v is not None else Counter() for v in state[r]]
    items = {r: list(program.items[r]) for r in range(n)}
    pcs = [0] * n

    def cur_comm(r):
        """Advance over local copies (they never block), apply them."""
        while pcs[r] < len(items[r]):
            it = items[r][pcs[r]]
            if isinstance(it, algomod.CopyItem):
                state[r][it.dst] = Counter(state[r][it.src])
                pcs[r] += 1
            else:
                return it
        return None

    while True:
        cur = {r: cur_comm(r) for r in range(n)}
        if all(c is None for c in cur.values()):
            return state

        def matched(r):
            e = cur[r]
            if e is None:
                return False
            if e.to >= 0 and (cur[e.to] is None or cur[e.to].frm != r):
                return False
            if e.frm >= 0 and (cur[e.frm] is None or cur[e.frm].to != r):
                return False
            return True

        done = [r for r in range(n) if matched(r)]
        if not done:
            return None  # deadlock
        payload = {
            r: [Counter(state[r][s]) for s in cur[r].send_slots]
            for r in done if cur[r].to >= 0
        }
        for r in done:
            e = cur[r]
            if e.frm < 0:
                continue
            for slot, val in zip(e.recv_slots, payload[e.frm]):
                if e.action == "reduce":
                    state[r][slot] = state[r][slot] + val
                else:
                    state[r][slot] = val
        for r in done:
            pcs[r] += 1


def _values_correct(program, state):
    if state is None:
        return False
    n = program.world
    for r in range(n):
        for c in range(program.chunks):
            want = algo_check._expected(
                program.spec.collective, n, r, c
            )
            if state[r][c] != want:
                return False
    return True


@pytest.mark.parametrize("stem", SHIPPED)
@pytest.mark.parametrize("world", WORLDS)
def test_coverage_interpreter_agrees_on_shipped(stem, world):
    program = algomod.expand(algomod.load(shipped_path(stem)), world)
    ok, advances, _ = simulate_rounds(algomod.events_for(program))
    assert ok
    assert algo_check.interpret_coverage(program, advances) == []
    assert _values_correct(program, _brute_force_values(program))


def test_coverage_agrees_with_brute_force_on_truncated_rings():
    """Property family: a ring whose reduce-scatter runs j laps and
    allgather m laps is correct iff j == m == n-1. The symbolic M4T204
    interpreter and the independent concrete-value interpreter must
    agree on all of them (1000 seeded draws)."""
    with open(shipped_path("ring")) as f:
        base = json.load(f)
    base.pop("expect", None)
    rng = random.Random(0xC0FE)
    outcomes = Counter()
    for seed in range(1000):
        rng.seed(seed)
        n = rng.choice((2, 3, 4, 5))
        j = rng.randint(1, n - 1) if n > 1 else 1
        m = rng.randint(0, n - 1)
        raw = copy.deepcopy(base)
        raw["worlds"] = [n]
        raw["phases"][0]["repeat"] = str(j)
        raw["phases"][1]["repeat"] = str(m)
        if m == 0:
            raw["phases"] = raw["phases"][:1]
        program = algomod.expand(algomod.parse(raw), n)
        ok, advances, _ = simulate_rounds(algomod.events_for(program))
        assert ok  # symmetric sendrecv rings never deadlock
        m204 = algo_check.interpret_coverage(program, advances)
        correct = _values_correct(program, _brute_force_values(program))
        assert (not m204) == correct, (
            f"seed {seed} (n={n} j={j} m={m}): symbolic interpreter "
            f"says {'clean' if not m204 else 'violation'}, brute force "
            f"says values {'correct' if correct else 'wrong'}"
        )
        assert (not m204) == (j == n - 1 and m == n - 1)
        outcomes[bool(m204)] += 1
    assert outcomes[True] > 100 and outcomes[False] > 100


# ---------------------------------------------------------------------
# costmodel + autotune integration (device-free half)
# ---------------------------------------------------------------------


def test_costmodel_serves_verified_step_structure():
    tag = algomod.load(shipped_path("ring")).tag
    c = costmodel.cost(
        "AllReduce", nbytes=1 << 20, dtype="float32", world=8, impl=tag,
    )
    assert c.get("impl") == tag
    assert c["steps"] == 14  # 2*(n-1) at n=8
    assert c["wire_bytes"] == 14 * -(-(1 << 20) // 8)  # ceil(b/chunks)
    assert "verified algo" in c["algorithm"]


def test_costmodel_ignores_algo_outside_its_proof():
    """Wrong op or an unproven world: the registry entry does not
    apply, and the model falls back to the default op cost (no impl
    stamp) instead of inventing numbers for an unverified config."""
    tag = algomod.load(shipped_path("ring")).tag
    c = costmodel.cost(
        "AllGather", nbytes=1 << 20, dtype="float32", world=8, impl=tag,
    )
    assert c.get("impl") != tag
    c = costmodel.cost(
        "AllReduce", nbytes=1 << 20, dtype="float32", world=16, impl=tag,
    )
    assert c.get("impl") != tag and c["steps"] == 30  # default ring


def test_autotune_candidates_include_registered_algos():
    tag = algomod.load(shipped_path("alltoall_twophase")).tag
    assert tag in planmod.impls_for("AllToAll")
    key = planmod.plan_key(
        "AllToAll", nbytes=1 << 16, dtype="float32", world=8,
        axes=("ranks",), platform="cpu",
    )
    cands = autotune.candidates(planmod.parse_key(key))
    assert any(impl == tag for impl, _params in cands)


def test_autotune_default_grid_unchanged_by_algo_registration():
    """Regression pin: registering algorithms must not silently grow
    the default tune grid (plan goldens + selftest determinism) —
    AllToAll keys join only via --ops or observed events."""
    keys = autotune.default_keys(platform="cpu", world=8)
    ops = {k.split("|")[0] for k in keys}
    assert ops == {"AllReduce", "ReduceScatter", "AllGather"}


def test_tune_sweep_over_alltoall_picks_verified_algo(tmp_path):
    """`planner tune --ops AllToAll` sweeps registered algorithms on
    equal footing and pins the winner with a costmodel-seeded entry."""
    out = str(tmp_path / "plan.json")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", "tune",
         "--ops", "AllToAll", "--world", "8", "--dtypes", "float32",
         "--cache", out],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=_clean_env(),
    )
    assert res.returncode == 0, res.stderr
    loaded = planmod.load(out)
    tag = algomod.load(shipped_path("alltoall_twophase")).tag
    a2a = {k: e for k, e in loaded.entries.items()
           if k.startswith("AllToAll|")}
    assert a2a, sorted(loaded.entries)
    assert any(e.impl == tag for e in a2a.values()), {
        k: e.impl for k, e in a2a.items()
    }


# ---------------------------------------------------------------------
# golden pin: shipped algorithm identity + compiled structure
# ---------------------------------------------------------------------


def _golden_payload():
    out = {}
    for stem in sorted(SHIPPED):
        spec = algomod.load(shipped_path(stem))
        per_world = {}
        for n in WORLDS:
            program = algomod.expand(spec, n)
            lowered = algomod.lower(program)
            per_world[str(n)] = {
                "rounds": len(lowered.rounds),
                "wire_chunks": lowered.wire_chunks,
                "chunks": program.chunks,
                "slots": program.slots,
                "event_fingerprints": sorted({
                    e.fingerprint
                    for evs in algomod.events_for(program).values()
                    for e in evs
                }),
            }
        out[stem] = {
            "name": spec.name,
            "collective": spec.collective,
            "fingerprint": spec.fingerprint,
            "tag": spec.tag,
            "per_world": per_world,
        }
    return out


def test_golden_pin():
    """Shipped algorithm identity (fingerprints -> registry tags ->
    plan entries) and compiled structure are frozen; an intentional
    change regenerates with `python tests/test_planner_algo.py --regen`
    plus fresh proofs."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert golden == _golden_payload()


# ---------------------------------------------------------------------
# CLI: planner algo {check,show,lower}
# ---------------------------------------------------------------------


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("M4T_ALGO_PATH", None)
    return env


def _planner(*argv, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.planner", *argv],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=_clean_env(),
    )


def test_cli_check_clean_file_exits_zero():
    res = _planner(
        "algo", "check", shipped_path("ring"), "--ranks", "2,4,8",
    )
    assert res.returncode == 0, res.stderr
    assert res.stdout.count("deadlock-free") == 3  # one per world


def test_cli_check_skips_proof_artifacts():
    # CI runs `algo check planner/algos/*.json`, which also globs the
    # committed .proof.json artifacts — they are outputs, not inputs
    res = _planner(
        "algo", "check",
        shipped_path("ring"),
        algomod.proof_path(shipped_path("ring")),
        "--ranks", "2,4,8",
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("deadlock-free") == 3
    assert "schema mismatch" not in res.stdout


def test_cli_check_deadlock_exits_one_with_witness():
    res = _planner("algo", "check", DEADLOCK_FIXTURE)
    assert res.returncode == 1
    assert "M4T201" in res.stdout and "rank cycle" in res.stdout


def test_cli_check_json_schema():
    res = _planner("algo", "check", BADCOV_FIXTURE, "--json")
    assert res.returncode == 1
    payload = json.loads(res.stdout)
    reports = payload if isinstance(payload, list) else payload["reports"]
    codes = {
        f["code"] for rep in reports for f in rep.get("findings", ())
    }
    assert "M4T204" in codes


def test_cli_check_sarif_names_rules():
    res = _planner("algo", "check", DEADLOCK_FIXTURE, "--sarif", "-")
    assert res.returncode == 1
    sarif = json.loads(res.stdout)
    run = sarif["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"M4T201", "M4T204", "M4T205"} <= rule_ids
    assert any(
        x["ruleId"] == "M4T201" for x in run["results"]
    )


def test_cli_show_lists_registry():
    res = _planner("algo", "show")
    assert res.returncode == 0, res.stderr
    for name in ("ring", "recursive-double", "alltoall-twophase"):
        assert name in res.stdout


def test_cli_lower_json_roundtrips():
    res = _planner(
        "algo", "lower", shipped_path("recursive_double"),
        "--ranks", "8", "--json",
    )
    assert res.returncode == 0, res.stderr
    payload = json.loads(res.stdout)
    lowered = payload["8"] if "8" in payload else payload
    assert lowered["wire_chunks"] == 3
    assert len(lowered["rounds"]) == 3


def test_cli_check_shipped_algos_at_non_power_of_two_worlds():
    """Satellite (PR 18): every shipped algorithm at worlds {3, 5, 6}
    either proves deadlock-free or names its infeasibility — no
    unexplained failures in the catalog."""
    res = _planner(
        "algo", "check", shipped_path("ring"), "--ranks", "3,5,6",
    )
    assert res.returncode == 0, res.stderr
    assert res.stdout.count("deadlock-free") == 3
    # the ring's round count is 2(n-1) at every world, pow2 or not
    for n, rounds in ((3, 4), (5, 8), (6, 10)):
        assert f"world={n} deadlock-free rounds={rounds}" in res.stdout


def test_cli_check_recursive_double_names_log2_infeasibility():
    res = _planner(
        "algo", "check", shipped_path("recursive_double"),
        "--ranks", "3,5,6",
    )
    assert res.returncode == 1
    for n in (3, 5, 6):
        assert f"log2({n}) is not an integer" in res.stdout, res.stdout


def test_cli_check_alltoall_twophase_names_rank_range_infeasibility():
    res = _planner(
        "algo", "check", shipped_path("alltoall_twophase"),
        "--ranks", "3,5,6",
    )
    assert res.returncode == 1
    # the stride pattern walks off the rank space at non-pow2 worlds;
    # the verdict names the exact phase, step, and offending rank
    assert "to 3 outside [0, 3)" in res.stdout
    assert "to 5 outside [0, 5)" in res.stdout
    assert "to 6 outside [0, 6)" in res.stdout
    assert "use -1 for PROC_NULL" in res.stdout


def _topo_file(tmp_path, world=8):
    from mpi4jax_tpu.observability import topology
    from mpi4jax_tpu.planner import placement

    path = str(tmp_path / "topo.json")
    topology.save(path, placement.adversarial_topo(world))
    return path


def test_cli_lower_topo_prints_per_round_drain_times(tmp_path):
    """Satellite (PR 18): ``algo lower --topo`` annotates every round
    with its drain time at the slowest measured edge — the
    ``expected_time_topo`` objective, one round at a time."""
    res = _planner(
        "algo", "lower", shipped_path("ring"), "--ranks", "8",
        "--topo", _topo_file(tmp_path),
    )
    assert res.returncode == 0, res.stderr
    round_lines = [
        ln for ln in res.stdout.splitlines()
        if ln.strip().startswith("round ")
    ]
    assert len(round_lines) == 14  # 2(n-1) rounds of the ring at n=8
    for ln in round_lines:
        assert "drain=" in ln and "us slowest=" in ln, ln


def test_cli_lower_topo_json_carries_drains(tmp_path):
    res = _planner(
        "algo", "lower", shipped_path("ring"), "--ranks", "4",
        "--topo", _topo_file(tmp_path, world=4), "--json",
    )
    assert res.returncode == 0, res.stderr
    payload = json.loads(res.stdout)
    drains = payload["4"]["topo_drains"]
    assert len(drains) == 6
    for d in drains:
        assert d["drain_s"] > 0
        src, dst = d["slowest_edge"]
        assert 0 <= src < 4 and 0 <= dst < 4


def test_cli_lower_topo_bad_map_exits_two(tmp_path):
    missing = str(tmp_path / "nope.json")
    res = _planner(
        "algo", "lower", shipped_path("ring"), "--ranks", "8",
        "--topo", missing,
    )
    assert res.returncode == 2
    assert missing in res.stderr


def test_rule_catalog_lists_all_simulation_rules():
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analysis", "--rules"],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env=_clean_env(),
    )
    assert res.returncode == 0
    for code in ("M4T201", "M4T202", "M4T203", "M4T204", "M4T205"):
        assert code in res.stdout, code


# ---------------------------------------------------------------------
# launch --verify --algo: the pre-spawn gate, end to end
# ---------------------------------------------------------------------


def _launch_verify(tmp_path, algo_file):
    target = str(tmp_path / "target.py")
    with open(target, "w") as f:
        f.write("print('RANK_RAN')\n")
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--verify", "--algo", algo_file, target],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env=_clean_env(),
    )


def test_launch_verify_blocks_deadlocking_algo_before_spawn(tmp_path):
    """Acceptance: the committed deadlock fixture is rejected by
    ``launch --verify`` with the M4T201 rank-cycle witness, exit 1,
    and no rank ever spawns."""
    res = _launch_verify(tmp_path, DEADLOCK_FIXTURE)
    assert res.returncode == 1
    assert "M4T201" in res.stderr and "rank cycle" in res.stderr
    assert "BLOCKED" in res.stderr
    assert "RANK_RAN" not in res.stdout


def test_launch_verify_blocks_coverage_violation(tmp_path):
    res = _launch_verify(tmp_path, BADCOV_FIXTURE)
    assert res.returncode == 1
    assert "M4T204" in res.stderr
    assert "missing contribution" in res.stderr
    assert "RANK_RAN" not in res.stdout


def test_launch_verify_admits_proven_algo_and_spawns(tmp_path):
    res = _launch_verify(tmp_path, shipped_path("ring"))
    assert res.returncode == 0, res.stderr
    # both ranks really ran (the --verify import itself prints once)
    assert res.stdout.count("RANK_RAN") >= 2


def test_launch_verify_blocks_plan_with_unproven_algo_impl(tmp_path):
    """An armed plan naming an algo impl with no registry backing is
    refused pre-spawn, not at the first collective."""
    key = planmod.plan_key(
        "AllReduce", nbytes=4096, dtype="float32", world=2,
        axes=("ranks",), platform="cpu",
    )
    p = planmod.Plan(platform="cpu")
    p.entries[key] = planmod.PlanEntry(
        impl="algo:phantom@0123456789abcdef", source="analytic",
    )
    plan_path = str(tmp_path / "plan.json")
    planmod.save(p, plan_path)
    target = str(tmp_path / "target.py")
    with open(target, "w") as f:
        f.write("print('RANK_RAN')\n")
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2",
         "--verify", "--plan", plan_path, target],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env=_clean_env(),
    )
    assert res.returncode == 1
    assert "not a registered" in res.stderr
    assert "BLOCKED" in res.stderr
    assert "RANK_RAN" not in res.stdout


if __name__ == "__main__":
    if "--regen" in sys.argv:
        with open(GOLDEN, "w") as f:
            json.dump(_golden_payload(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"regenerated {GOLDEN}")
    else:
        print(__doc__)
