"""CLI contract (exit 0 clean / 1 findings / 2 error) and the
``doctor --static`` fingerprint join between runtime MISMATCH
verdicts and static CollectiveSites."""

import json
import os

import pytest

from mpi4jax_tpu.analysis.__main__ import main as lint_main
from mpi4jax_tpu.observability import doctor

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(HERE, "data", "lint_fixture.py")

CLEAN_SRC = '''
import jax.numpy as jnp
import mpi4jax_tpu as m4t

def step(x):
    return m4t.allreduce(x)
'''

BAD_SRC = '''
import jax.numpy as jnp
from jax import lax
import mpi4jax_tpu as m4t

def step(x):
    r = lax.axis_index("ranks")
    return lax.cond(r == 0, lambda v: m4t.allreduce(v), lambda v: v, x)
'''


def _write(tmp_path, name, src):
    path = tmp_path / name
    path.write_text(src)
    return str(path)


# -- python -m mpi4jax_tpu.analysis -----------------------------------


def test_cli_clean_exits_0(tmp_path, capsys):
    target = _write(tmp_path, "clean_mod.py", CLEAN_SRC)
    rc = lint_main([f"{target}:step", "--arg", "f32[16]"])
    assert rc == 0
    assert "clean: no findings" in capsys.readouterr().out


def test_cli_findings_exit_1_and_name_the_line(tmp_path, capsys):
    target = _write(tmp_path, "bad_mod.py", BAD_SRC)
    rc = lint_main([f"{target}:step", "--arg", "f32[16]"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "M4T101" in out
    assert "bad_mod.py:8" in out  # the cond line


def test_cli_json_report(tmp_path, capsys):
    target = _write(tmp_path, "bad_mod2.py", BAD_SRC)
    rc = lint_main([f"{target}:step", "--arg", "f32[16]", "--json"])
    assert rc == 1
    obj = json.loads(capsys.readouterr().out)
    assert obj["version"] == 1
    assert obj["n_findings"] >= 1
    assert obj["reports"][0]["findings"][0]["code"] == "M4T101"


def test_cli_axis_override(tmp_path, capsys):
    target = _write(tmp_path, "clean_mod2.py", CLEAN_SRC)
    rc = lint_main(
        [f"{target}:step", "--arg", "f32[16]", "--axis", "ranks=4"]
    )
    assert rc == 0
    assert "'ranks': 4" in capsys.readouterr().out


def test_cli_axis_none_lints_launcher_world_resolution(tmp_path, capsys):
    # --axis none: no bound axes, the multi-controller/shm resolution;
    # fingerprints carry @<none> like the shm backend's runtime records
    target = _write(tmp_path, "clean_mod5.py", CLEAN_SRC)
    rc = lint_main([f"{target}:step", "--arg", "f32[16]", "--axis", "none"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "@<none>" in out


def test_cli_axis_none_exclusive(tmp_path, capsys):
    target = _write(tmp_path, "clean_mod6.py", CLEAN_SRC)
    rc = lint_main(
        [f"{target}:step", "--axis", "none", "--axis", "ranks=8"]
    )
    assert rc == 2


def test_cli_module_targets_registry(capsys):
    rc = lint_main([FIXTURE])
    assert rc == 1  # the fixture's divergent target has findings
    out = capsys.readouterr().out
    assert "lint_fixture:clean" in out
    assert "lint_fixture:divergent" in out


def test_cli_unimportable_target_exits_2(tmp_path, capsys):
    rc = lint_main([str(tmp_path / "nope.py")])
    assert rc == 2
    assert "cannot resolve" in capsys.readouterr().err


def test_cli_missing_function_exits_2(tmp_path, capsys):
    target = _write(tmp_path, "clean_mod3.py", CLEAN_SRC)
    rc = lint_main([f"{target}:no_such_fn"])
    assert rc == 2


def test_cli_untraceable_exits_2(tmp_path, capsys):
    target = _write(tmp_path, "clean_mod4.py", CLEAN_SRC)
    # wrong rank: bad arg spec shape triggers a trace error, not findings
    rc = lint_main([f"{target}:step", "--arg", "zzz[16]"])
    assert rc == 2


def test_cli_rules_listing(capsys):
    rc = lint_main(["--rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for code in ("M4T101", "M4T102", "M4T103", "M4T104", "M4T105", "M4T106"):
        assert code in out


def test_cli_no_registry_module_exits_2(tmp_path, capsys):
    target = _write(tmp_path, "bare_mod.py", "x = 1\n")
    rc = lint_main([target])
    assert rc == 2
    assert "M4T_LINT_TARGETS" in capsys.readouterr().err


# -- doctor --static ---------------------------------------------------


def _emission(rank, seq, op, t):
    return {
        "kind": "emission", "rank": rank, "seq": seq, "op": op,
        "shape": [8], "dtype": "float32", "axes": ["ranks"],
        "world": 3, "bytes": 32, "cid": f"c{rank:02d}{seq:04d}", "t": t,
    }


def _mismatch_rundir(tmp_path):
    """3 ranks; rank 2 diverges at seq 2 (AllGather vs AllReduce) —
    both fingerprints exist as static sites in lint_fixture's clean
    target, so the join can name their source lines."""
    logs = {
        0: [_emission(0, 1, "AllReduce", 100.0),
            _emission(0, 2, "AllReduce", 101.0)],
        1: [_emission(1, 1, "AllReduce", 100.0),
            _emission(1, 2, "AllReduce", 101.0)],
        2: [_emission(2, 1, "AllReduce", 100.0),
            _emission(2, 2, "AllGather", 101.0)],
    }
    for rank, records in logs.items():
        with open(tmp_path / f"events-rank{rank}.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return str(tmp_path)


def test_doctor_static_joins_mismatch_to_source_line(tmp_path, capsys):
    d = _mismatch_rundir(tmp_path)
    rc = doctor.main([d, "--static", FIXTURE])
    captured = capsys.readouterr()
    assert rc == 1  # findings
    assert "MISMATCH at seq 2" in captured.out
    # both fingerprint groups resolve to lint_fixture source lines
    assert "declared at" in captured.out
    assert "lint_fixture.py:25" in captured.out  # allreduce line
    assert "lint_fixture.py:26" in captured.out  # allgather line
    assert "fingerprint join" in captured.err


def test_doctor_static_json_carries_static_sites(tmp_path, capsys):
    d = _mismatch_rundir(tmp_path)
    rc = doctor.main([d, "--static", FIXTURE, "--json"])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    (mismatch,) = [
        f for f in report["findings"] if f["kind"] == "mismatch"
    ]
    for group in mismatch["groups"]:
        assert "static_sites" in group
        assert group["static_sites"], group
        assert "lint_fixture.py" in group["static_sites"][0]["source"]


def test_doctor_static_unmatched_fingerprint_says_so(tmp_path, capsys):
    logs = {
        0: [_emission(0, 1, "AllReduce", 100.0)],
        1: [dict(_emission(1, 1, "AllReduce", 100.0), shape=[999])],
    }
    for rank, records in logs.items():
        with open(tmp_path / f"events-rank{rank}.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    rc = doctor.main([str(tmp_path), "--static", FIXTURE])
    captured = capsys.readouterr()
    assert rc == 1
    assert "no static site with this fingerprint" in captured.out


def test_doctor_static_bad_target_exits_2(tmp_path, capsys):
    d = _mismatch_rundir(tmp_path)
    rc = doctor.main([d, "--static", str(tmp_path / "missing_mod.py")])
    assert rc == 2
    assert "--static failed" in capsys.readouterr().err


def test_doctor_without_static_unchanged(tmp_path, capsys):
    d = _mismatch_rundir(tmp_path)
    rc = doctor.main([d])
    captured = capsys.readouterr()
    assert rc == 1
    assert "declared at" not in captured.out


# -- conftest leak fixture (the teardown token-discipline check) ------


@pytest.mark.allow_pending_sends
def test_leak_optout_marker_allows_pending_sends():
    import warnings

    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4t
    from mpi4jax_tpu import token

    n = 8
    dest = [(r + 1) % n for r in range(n)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        jax.make_jaxpr(
            lambda x: (m4t.send(x, dest), x)[1], axis_env=[("ranks", n)]
        )(jnp.zeros((4,), jnp.float32))
    # the leak exists now; the autouse fixture must swallow it because
    # of the marker (and drain it so nothing bleeds into later tests)
    assert any(st.pending_sends for st in token._states)


def test_drain_pending_sends_clears_all_states():
    from mpi4jax_tpu import token

    assert token.drain_pending_sends() == []
