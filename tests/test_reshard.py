"""Elastic world-size resharding (``mpi4jax_tpu/resilience/reshard.py``
+ the ``m4t-ckpt/2`` sharded checkpoint schema).

Covers the ISSUE-9 acceptance surface:

- partition math properties (cover, contiguous, balanced — M ∤ N
  included) and :class:`LeafSpec` validation / JSON round trip;
- plan properties over seeded random layouts × random N→M pairs:
  every destination index covered exactly once, transfers ordered,
  replicated leaves one copy per destination;
- metered execution: the executor's **measured** peak scratch equals
  the plan's :meth:`ReshardPlan.peak_scratch_bytes` exactly and never
  exceeds the 2-shard :meth:`ReshardPlan.memory_bound_bytes` — the
  bound is asserted, not claimed;
- round trip N→M→N is bit-identical; resharded shards equal direct
  global slicing; opaque (non-portable) dtypes reshard as raw bytes;
- ``m4t-ckpt/2``: manifest fields, per-rank ``.npy`` layout, torn
  shard detection, ``latest_valid(allow_reshard=)`` returning a
  world-mismatched checkpoint as an explicit *reshard candidate*
  (and logging the skip otherwise — never silent);
- the two-phase (per-rank) stage/commit protocol;
- :func:`reshard_checkpoint` end to end with provenance, and the
  ``python -m mpi4jax_tpu.resilience reshard`` CLI (selftest,
  dry-run, commit, error paths);
- the on-mesh executor over the existing p2p ops (2-rank launcher
  world resharding a 4-world checkpoint; native-gated).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from mpi4jax_tpu.resilience import ckpt, reshard
from mpi4jax_tpu.resilience.reshard import (
    LeafSpec,
    MemoryMeter,
    ReshardError,
    execute_plan,
    plan_reshard,
    reader_from_global,
    reader_from_shards,
    reshard_flat,
    shard_extent,
    shard_slices,
    spec_for_array,
    specs_fingerprint,
)

pytestmark = [pytest.mark.resilience, pytest.mark.elastic]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# partition math
# ---------------------------------------------------------------------


@pytest.mark.parametrize("length", [0, 1, 2, 5, 8, 17, 64, 101])
@pytest.mark.parametrize("world", [1, 2, 3, 4, 7, 13])
def test_shard_extent_properties(length, world):
    spans = [shard_extent(length, world, r) for r in range(world)]
    # cover [0, length) contiguously, in rank order
    assert spans[0][0] == 0 and spans[-1][1] == length
    for (_, b), (c, _) in zip(spans, spans[1:]):
        assert b == c
    # balanced: sizes differ by at most one, bigger shards first
    sizes = [b - a for a, b in spans]
    assert max(sizes) - min(sizes) <= 1
    assert sizes == sorted(sizes, reverse=True)


def test_shard_extent_errors():
    with pytest.raises(ReshardError, match="world"):
        shard_extent(8, 0, 0)
    with pytest.raises(ReshardError, match="out of range"):
        shard_extent(8, 2, 2)


def test_leafspec_validation_and_json():
    s = LeafSpec(shape=(4, 6), dtype="float32", axis=1)
    assert s.itemsize == 4 and s.nbytes == 4 * 6 * 4
    s2 = LeafSpec.from_json(s.to_json())
    assert s2 == s
    with pytest.raises(ReshardError, match="scalar"):
        LeafSpec(shape=(), dtype="float32", kind="sharded")
    with pytest.raises(ReshardError, match="axis"):
        LeafSpec(shape=(4,), dtype="float32", axis=1)
    with pytest.raises(ReshardError, match="kind"):
        LeafSpec(shape=(4,), dtype="float32", kind="diagonal")
    with pytest.raises(ReshardError, match="itemsize"):
        LeafSpec(shape=(4,), dtype="no_such_dtype")
    # unconstructible dtype is fine with an explicit itemsize
    s3 = LeafSpec(shape=(4,), dtype="mystery16", itemsize=2)
    assert s3.wire_dtype() == np.dtype("V2")
    # replicated scalars are fine
    LeafSpec(shape=(), dtype="int32", kind="replicated")


def test_specs_fingerprint_world_independent_and_order_free():
    a = {"x": LeafSpec(shape=(8, 2), dtype="float32"),
         "y": LeafSpec(shape=(3,), dtype="int32", kind="replicated")}
    b = dict(reversed(list(a.items())))
    assert specs_fingerprint(a) == specs_fingerprint(b)
    # no world anywhere in the identity: that is the point
    c = {"x": LeafSpec(shape=(8, 2), dtype="float64"),
         "y": a["y"]}
    assert specs_fingerprint(a) != specs_fingerprint(c)


def test_spec_for_array():
    s = spec_for_array(np.zeros((3, 5), np.int16), axis=1)
    assert s.shape == (3, 5) and s.dtype == "int16" and s.itemsize == 2


# ---------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------


def _random_case(rng):
    specs, flat = {}, {}
    for i in range(rng.randint(1, 5)):
        nd = rng.randint(1, 4)
        shape = tuple(int(rng.randint(1, 10)) for _ in range(nd))
        kind = "replicated" if rng.rand() < 0.25 else "sharded"
        axis = int(rng.randint(0, nd)) if kind == "sharded" else 0
        dtype = rng.choice(["float32", "int32", "float64", "int8"])
        key = f"leaf{i}"
        specs[key] = LeafSpec(shape=shape, dtype=dtype, kind=kind,
                              axis=axis)
        flat[key] = (rng.randn(*shape) * 50).astype(dtype)
    return specs, flat


def test_plan_covers_every_destination_exactly_once():
    rng = np.random.RandomState(1)
    for _ in range(25):
        specs, _ = _random_case(rng)
        n, m = int(rng.randint(1, 8)), int(rng.randint(1, 8))
        plan = plan_reshard(specs, n, m)
        for key, spec in specs.items():
            ts = plan.transfers[key]
            if spec.kind == "replicated":
                assert [t.dst_rank for t in ts] == list(range(m))
                assert all(t.nbytes == spec.nbytes for t in ts)
                assert all(0 <= t.src_rank < n for t in ts)
                continue
            for d in range(m):
                dlo, dhi = shard_extent(spec.shape[spec.axis], m, d)
                mine = [t for t in ts if t.dst_rank == d]
                covered = dlo
                for t in mine:  # plan order is (dst, src): already sorted
                    assert t.lo == covered
                    assert 0 <= t.src_rank < n
                    covered = t.hi
                assert covered == dhi


def test_plan_world_errors():
    with pytest.raises(ReshardError, match=">= 1"):
        plan_reshard({"x": LeafSpec(shape=(4,), dtype="f4")}, 0, 2)


# ---------------------------------------------------------------------
# metered execution: correctness + the asserted memory bound
# ---------------------------------------------------------------------


def test_execute_matches_direct_slicing_and_round_trips():
    rng = np.random.RandomState(2)
    for _ in range(20):
        specs, flat = _random_case(rng)
        n, m = int(rng.randint(1, 7)), int(rng.randint(1, 7))
        out = reshard_flat(flat, specs, n, m)
        for key, spec in specs.items():
            for d in range(m):
                np.testing.assert_array_equal(
                    out[key, d].view(flat[key].dtype),
                    flat[key][shard_slices(spec, m, d)],
                )
        # round trip back to n, starting from the m-shards
        plan_back = plan_reshard(specs, m, n)
        back = {}
        execute_plan(
            plan_back, reader_from_shards(out, specs, m),
            lambda k, d, a: back.__setitem__((k, d), a),
        )
        for key, spec in specs.items():
            for r in range(n):
                np.testing.assert_array_equal(
                    back[key, r].view(flat[key].dtype),
                    flat[key][shard_slices(spec, n, r)],
                )


def test_peak_memory_is_metered_and_bounded():
    """The acceptance bullet: peak per-rank scratch is *asserted*
    against the planned schedule, not claimed."""
    rng = np.random.RandomState(3)
    for _ in range(20):
        specs, flat = _random_case(rng)
        n, m = int(rng.randint(1, 7)), int(rng.randint(1, 7))
        plan = plan_reshard(specs, n, m)
        shards = {
            (k, r): np.ascontiguousarray(
                flat[k][shard_slices(s, n, r)])
            for k, s in specs.items() for r in range(n)
        }
        meter = MemoryMeter()
        execute_plan(
            plan, reader_from_shards(shards, specs, n),
            lambda k, d, a: None, meter=meter,
        )
        assert meter.live == 0  # everything freed
        assert meter.peak == plan.max_peak_bytes()
        assert meter.peak <= plan.memory_bound_bytes()


def test_peak_memory_exact_numbers():
    """One hand-checkable case: 12×f32 over 3 ranks → 2 ranks.
    dst shards are 6 elements (24 B); the largest staged slice is one
    whole source shard (4 elements, 16 B) → peak 40 B, bound
    2 × 24 B = 48 B."""
    specs = {"w": LeafSpec(shape=(12,), dtype="float32")}
    plan = plan_reshard(specs, 3, 2)
    assert plan.peak_scratch_bytes() == {0: 24 + 16, 1: 24 + 16}
    assert plan.memory_bound_bytes() == 48
    flat = {"w": np.arange(12, dtype=np.float32)}
    meter = MemoryMeter()
    out = {}
    execute_plan(
        plan, reader_from_global(flat, specs, 3),
        lambda k, d, a: out.__setitem__((k, d), a), meter=meter,
    )
    assert meter.peak == 40
    np.testing.assert_array_equal(out["w", 0], flat["w"][:6])
    np.testing.assert_array_equal(out["w", 1], flat["w"][6:])


def test_execute_dst_ranks_subset_and_errors():
    specs = {"w": LeafSpec(shape=(8,), dtype="float32")}
    flat = {"w": np.arange(8, dtype=np.float32)}
    plan = plan_reshard(specs, 2, 4)
    out = {}
    execute_plan(
        plan, reader_from_global(flat, specs, 2),
        lambda k, d, a: out.__setitem__((k, d), a), dst_ranks=[2],
    )
    assert list(out) == [("w", 2)]
    np.testing.assert_array_equal(out["w", 2], flat["w"][4:6])
    with pytest.raises(ReshardError, match="out of range"):
        execute_plan(
            plan, reader_from_global(flat, specs, 2),
            lambda k, d, a: None, dst_ranks=[4],
        )


def test_opaque_dtype_moves_raw_bytes():
    spec = LeafSpec(shape=(6, 2), dtype="mystery16", itemsize=2)
    raw = np.arange(12, dtype=np.uint16).reshape(6, 2).view("V2")
    out = reshard_flat({"x": raw}, {"x": spec}, 2, 3)
    merged = np.concatenate(
        [out["x", r].view(np.uint16) for r in range(3)], axis=0
    )
    np.testing.assert_array_equal(merged, raw.view(np.uint16))


def test_bfloat16_reshard_via_portable_wire():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    bf16 = np.dtype(ml_dtypes.bfloat16)
    arr = np.arange(8, dtype=np.float32).astype(bf16)
    spec = spec_for_array(arr)
    # storage dtype is portable even though the logical one is not
    assert spec.wire_dtype() == np.dtype("V2")
    out = reshard_flat({"x": arr}, {"x": spec}, 1, 2)
    merged = np.concatenate(
        [out["x", r].view(bf16) for r in range(2)]
    )
    np.testing.assert_array_equal(merged, arr)


# ---------------------------------------------------------------------
# m4t-ckpt/2: layout, validity, reshard candidates
# ---------------------------------------------------------------------


def _demo_state():
    specs = {
        "w": LeafSpec(shape=(10, 3), dtype="float32"),
        "b": LeafSpec(shape=(3,), dtype="float32", kind="replicated"),
    }
    flat = {
        "w": np.arange(30, dtype=np.float32).reshape(10, 3),
        "b": np.ones(3, np.float32),
    }
    return specs, flat


def test_save_sharded_layout_and_manifest(tmp_path):
    specs, flat = _demo_state()
    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), keep=3, world=4)
    info = mgr.save_sharded(7, flat, specs)
    assert info.schema == "m4t-ckpt/2" and info.world == 4
    assert info.sharded and not info.world_mismatch
    manifest = json.load(open(os.path.join(info.path, "manifest.json")))
    assert manifest["schema"] == "m4t-ckpt/2"
    assert manifest["world"] == 4
    assert manifest["fingerprint"] == specs_fingerprint(specs)
    assert set(manifest["leaves"]) == {"w", "b"}
    assert manifest["leaves"]["w"]["shape"] == [10, 3]
    assert manifest["leaves"]["b"]["kind"] == "replicated"
    # on-disk layout: per-rank dirs for sharded, one dir for replicated
    data = sorted(os.listdir(info.data_path))
    assert data == ["rank00000", "rank00001", "rank00002", "rank00003",
                    "replicated"]
    # per-rank shard contents match direct slicing
    for r in range(4):
        sh = ckpt.load_shard(info, r)
        np.testing.assert_array_equal(
            sh["w"], flat["w"][shard_slices(specs["w"], 4, r)])
        np.testing.assert_array_equal(sh["b"], flat["b"])
    g = ckpt.load_sharded_global(info)
    np.testing.assert_array_equal(g["w"], flat["w"])


def test_v2_torn_shard_reads_as_invalid(tmp_path):
    specs, flat = _demo_state()
    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), keep=5, world=2)
    mgr.save_sharded(1, flat, specs)
    mgr.save_sharded(2, flat, specs)
    # delete one shard file of the newest: it must be skipped, older wins
    doomed = os.path.join(
        mgr.root, "step_00000002", "data", "rank00001", "leaf00001.npy"
    )
    os.unlink(doomed)
    info = mgr.latest_valid(world=2)
    assert info is not None and info.step == 1


def test_world_mismatch_logged_never_silent(tmp_path, capfd):
    """The satellite: a world-mismatched but otherwise-valid
    checkpoint must be reported, never indistinguishable from 'no
    checkpoint'."""
    specs, flat = _demo_state()
    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), keep=3, world=4)
    mgr.save_sharded(5, flat, specs)
    two = ckpt.CheckpointManager(str(tmp_path / "c"), keep=3, world=2)
    assert two.latest_valid(world=2) is None
    err = capfd.readouterr().err
    assert "skipping otherwise-valid checkpoint step 5" in err
    assert "world 4 != wanted 2" in err and "allow_reshard" in err
    # under the flag it comes back as an explicit candidate
    cand = two.latest_valid(world=2, allow_reshard=True)
    assert cand is not None and cand.world_mismatch and cand.world == 4
    at = two.at_step(5, world=2, allow_reshard=True)
    assert at is not None and at.world_mismatch
    # restore() refuses sharded checkpoints with a pointer to the API
    with pytest.raises(ValueError, match="load_shard"):
        two.restore(cand, None)


def test_v1_checkpoints_still_readable_beside_v2(tmp_path):
    def _json_save(path, state):
        with open(path, "w") as f:
            json.dump(state, f)

    def _json_restore(path, template):
        with open(path) as f:
            return json.load(f)

    mgr = ckpt.CheckpointManager(
        str(tmp_path / "c"), keep=5, world=2,
        save_fn=_json_save, restore_fn=_json_restore,
    )
    mgr.save(1, {"w": [1, 2]}, fingerprint="fp")
    specs, flat = _demo_state()
    mgr.save_sharded(2, flat, specs)
    newest = mgr.latest_valid(world=2)
    assert newest.step == 2 and newest.sharded
    old = mgr.at_step(1, world=2)
    assert old is not None and not old.sharded
    assert mgr.restore(old, None) == {"w": [1, 2]}
    # a v1 checkpoint is never a reshard candidate material: the
    # caller sees world_mismatch + sharded=False and knows
    mgr4 = ckpt.CheckpointManager(str(tmp_path / "c"), keep=5, world=4)
    cand = mgr4.at_step(1, world=4, allow_reshard=True)
    assert cand is not None and cand.world_mismatch and not cand.sharded


def test_two_phase_stage_commit(tmp_path):
    specs = {"w": LeafSpec(shape=(7,), dtype="float32"),
             "s": LeafSpec(shape=(), dtype="int32", kind="replicated")}
    g = np.arange(7, dtype=np.float32)
    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), keep=2, world=3)
    # commit before staging completes must refuse
    mgr.stage_shard(4, 0, {"w": g[:3], "s": np.int32(4)}, specs)
    with pytest.raises(RuntimeError, match="incomplete"):
        mgr.commit_sharded(4, specs)
    for r in (1, 2):
        lo, hi = shard_extent(7, 3, r)
        mgr.stage_shard(4, r, {"w": g[lo:hi], "s": np.int32(4)}, specs)
    info = mgr.commit_sharded(4, specs)
    assert info.step == 4 and info.world == 3
    # stage swept after commit
    assert not any(
        n.startswith(".stage-") for n in os.listdir(mgr.root))
    np.testing.assert_array_equal(
        ckpt.load_sharded_global(info)["w"], g)
    # wrong local shard shape is a loud error
    with pytest.raises(ValueError, match="shard shape"):
        mgr.stage_shard(5, 0, {"w": g, "s": np.int32(5)}, specs)


def test_reshard_checkpoint_round_trip_and_provenance(tmp_path):
    specs, flat = _demo_state()
    mgr4 = ckpt.CheckpointManager(str(tmp_path / "c"), keep=3, world=4)
    mgr4.save_sharded(9, flat, specs)
    mgr3 = ckpt.CheckpointManager(str(tmp_path / "c"), keep=3, world=3)
    cand = mgr3.latest_valid(world=3, allow_reshard=True)
    new = reshard.reshard_checkpoint(mgr3, cand, 3)
    assert new.world == 3 and new.step == 9
    prov = new.manifest["resharded_from"]
    assert prov["world"] == 4 and prov["step"] == 9
    assert prov["plan"]["peak_scratch_bytes"] <= (
        prov["plan"]["memory_bound_bytes"])
    np.testing.assert_array_equal(
        ckpt.load_sharded_global(new)["w"], flat["w"])
    # back to 4: bit-identical global state
    back = reshard.reshard_checkpoint(
        mgr4, mgr4.latest_valid(world=4, allow_reshard=True), 4)
    np.testing.assert_array_equal(
        ckpt.load_sharded_global(back)["w"], flat["w"])
    for r in range(4):
        np.testing.assert_array_equal(
            ckpt.load_shard(back, r)["w"],
            flat["w"][shard_slices(specs["w"], 4, r)])


def test_reshard_checkpoint_rejects_v1(tmp_path):
    def _json_save(path, state):
        with open(path, "w") as f:
            json.dump(state, f)

    mgr = ckpt.CheckpointManager(
        str(tmp_path / "c"), keep=3, world=4, save_fn=_json_save,
    )
    mgr.save(3, {"w": [1]}, fingerprint="fp")
    cand = ckpt.CheckpointManager(
        str(tmp_path / "c"), keep=3, world=2
    ).latest_valid(world=2, allow_reshard=True)
    with pytest.raises(ReshardError, match="m4t-ckpt/2"):
        reshard.reshard_checkpoint(mgr, cand, 2)


# ---------------------------------------------------------------------
# the reshard CLI
# ---------------------------------------------------------------------


def _run_cli(*argv, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.resilience", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_cli_reshard_selftest():
    res = _run_cli("reshard", "--selftest")
    assert res.returncode == 0, res.stderr
    assert "reshard selftest ok" in res.stdout


def test_cli_reshard_dry_run_and_commit(tmp_path):
    specs, flat = _demo_state()
    root = str(tmp_path / "c")
    ckpt.CheckpointManager(root, keep=3, world=4).save_sharded(
        6, flat, specs)
    res = _run_cli(
        "reshard", root, "--world", "2", "--dry-run", "--json")
    assert res.returncode == 0, res.stderr
    summary = json.loads(res.stdout)
    assert summary["step"] == 6
    assert summary["src_world"] == 4 and summary["dst_world"] == 2
    assert summary["peak_scratch_bytes"] <= summary["memory_bound_bytes"]
    # dry run wrote nothing
    info = ckpt.CheckpointManager(root, world=4).latest_valid(world=4)
    assert info is not None
    # the real thing
    res2 = _run_cli("reshard", root, "--world", "2")
    assert res2.returncode == 0, res2.stderr
    assert "committed step 6 at world 2" in res2.stderr
    info2 = ckpt.CheckpointManager(root, world=2).latest_valid(world=2)
    assert info2 is not None and info2.world == 2
    np.testing.assert_array_equal(
        ckpt.load_sharded_global(info2)["w"], flat["w"])


def test_cli_reshard_out_root_leaves_source_untouched(tmp_path):
    specs, flat = _demo_state()
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    ckpt.CheckpointManager(src, keep=3, world=4).save_sharded(
        2, flat, specs)
    res = _run_cli("reshard", src, "--world", "3", "--out", dst)
    assert res.returncode == 0, res.stderr
    assert ckpt.CheckpointManager(src, world=4).latest_valid(
        world=4).world == 4
    assert ckpt.CheckpointManager(dst, world=3).latest_valid(
        world=3).world == 3


def test_cli_reshard_error_paths(tmp_path):
    # no checkpoint at all
    res = _run_cli("reshard", str(tmp_path / "empty"), "--world", "2")
    assert res.returncode == 2
    assert "no valid checkpoint" in res.stderr
    # v1 checkpoint: clear schema message
    root = str(tmp_path / "v1")

    def _json_save(path, state):
        with open(path, "w") as f:
            json.dump(state, f)

    ckpt.CheckpointManager(root, world=4, save_fn=_json_save).save(
        1, {"w": [1]}, fingerprint="fp")
    res2 = _run_cli("reshard", root, "--world", "2")
    assert res2.returncode == 1
    assert "m4t-ckpt/2" in res2.stderr


# ---------------------------------------------------------------------
# on-mesh execution (existing collective ops; local + native paths)
# ---------------------------------------------------------------------


def test_on_mesh_local_copies_without_comm():
    """dst_world=1 makes every transfer a local copy: the on-mesh
    walker is validated device-free (send/recv never called)."""
    specs = {"w": LeafSpec(shape=(9,), dtype="float32"),
             "b": LeafSpec(shape=(2,), dtype="float32",
                           kind="replicated")}
    flat = {"w": np.arange(9, dtype=np.float32),
            "b": np.ones(2, np.float32)}
    plan = plan_reshard(specs, 3, 1)

    def boom(*a, **k):  # no wire traffic may happen
        raise AssertionError("p2p op called in an all-local reshard")

    out = reshard.execute_plan_on_mesh(
        plan, 0, reader_from_global(flat, specs, 3),
        src_owner=lambda s: 0, send_fn=boom, recv_fn=boom,
    )
    np.testing.assert_array_equal(out["w"], flat["w"])
    np.testing.assert_array_equal(out["b"], flat["b"])


needs_native = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain",
)


@needs_native
def test_on_mesh_p2p_reshard_matches_offline(tmp_path):
    """A live 2-rank world reshards a 4-world state through
    ``m4t.send``/``m4t.recv``: survivor r holds old shards r and r+2,
    every rank walks the same plan order, and each destination shard
    must equal direct global slicing."""
    script = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import numpy as np
        import mpi4jax_tpu as m4t
        from mpi4jax_tpu.runtime import shm
        from mpi4jax_tpu.resilience.reshard import (
            LeafSpec, plan_reshard, execute_plan_on_mesh,
            reader_from_shards, shard_slices,
        )

        rank, size = shm.rank(), shm.size()
        assert size == 2
        specs = {{"w": LeafSpec(shape=(10,), dtype="float32"),
                  "b": LeafSpec(shape=(3,), dtype="float32",
                                kind="replicated")}}
        g = {{"w": np.arange(10, dtype=np.float32) * 2.0,
              "b": np.asarray([7.0, 8.0, 9.0], np.float32)}}
        # survivor r holds old-world shards r and r + 2
        shards = {{
            (k, s): np.ascontiguousarray(g[k][shard_slices(spec, 4, s)])
            for k, spec in specs.items() for s in range(4)
            if s % 2 == rank
        }}
        plan = plan_reshard(specs, 4, 2)
        out = execute_plan_on_mesh(
            plan, rank, reader_from_shards(shards, specs, 4),
            src_owner=lambda s: s % 2,
        )
        np.testing.assert_array_equal(
            out["w"], g["w"][5 * rank:5 * (rank + 1)])
        np.testing.assert_array_equal(out["b"], g["b"])
        m4t.barrier()
        print(f"ONMESH{{rank}} OK")
    """)
    path = str(tmp_path / "onmesh.py")
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.launch", "-n", "2", path],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    assert "ONMESH0 OK" in res.stdout and "ONMESH1 OK" in res.stdout


# ---------------------------------------------------------------------
# tier-1 wiring for the package selftests
# ---------------------------------------------------------------------


def test_selftest_function_direct():
    assert reshard.selftest() == 0
