"""Multi-process ``jax.distributed`` leg: the XLA-collective path in
the reference's own execution regime — one process per rank, each
tracing and compiling its program independently.

This is the regime where channel-id assignment across separately
compiled programs can actually fail (SURVEY.md §7 hard part: HLO
collectives are matched by channel id across programs; a mismatch
deadlocks) — the single-process 8-device mesh used by the rest of the
suite can never exhibit it. The reference covers it by running its
suite under ``mpirun -np 2`` (``docs/developers.rst:18-27``,
``.github/workflows/mpi-tests.yml``); here each test spawns real
processes that rendezvous through a local coordinator and run
collectives over jaxlib's gloo CPU transport.
"""

import os
import socket
import subprocess
import sys
import textwrap


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = """
import os, sys
sys.path.insert(0, {repo!r})
rank = int(sys.argv[1])
nprocs = int(sys.argv[2])
port = sys.argv[3]
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
from mpi4jax_tpu.parallel import initialize
initialize(f"localhost:{{port}}", num_processes=nprocs, process_id=rank)
import numpy as np
import jax.numpy as jnp
import mpi4jax_tpu as m4t
from mpi4jax_tpu.parallel import local_blocks, spmd, world_mesh
assert len(jax.devices()) == nprocs, jax.devices()
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_world(n, script, timeout=300):
    """Spawn ``n`` processes running ``_PRELUDE + script``; returns the
    per-rank CompletedProcess list."""
    path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"m4t_dist_{os.getpid()}.py"
    )
    with open(path, "w") as f:
        f.write(_PRELUDE.format(repo=REPO))
        f.write(textwrap.dedent(script))
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, path, str(r), str(n), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
        )
        for r in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
        os.remove(path)
    return outs


def _assert_ok(outs, marker):
    for r, (rc, out) in enumerate(outs):
        assert rc == 0, f"rank {r} exited {rc}:\n{out}"
        assert f"{marker}{r}" in out, f"rank {r} missing {marker}:\n{out}"



def test_distributed_collective_pipeline():
    # allreduce + alltoall + sendrecv + bcast in one jitted program,
    # compiled independently by each process: any channel-id divergence
    # between the two compilations deadlocks the world (caught by the
    # subprocess timeout).
    outs = run_world(
        2,
        """
        n = nprocs
        mesh = world_mesh()
        ring_dst = tuple((r + 1) % n for r in range(n))
        ring_src = tuple((r - 1) % n for r in range(n))

        def pipeline(x, blocks):
            s = m4t.allreduce(x, op=m4t.SUM)
            t = m4t.alltoall(blocks)
            u = m4t.sendrecv(s, s, ring_src, ring_dst)
            v = m4t.bcast(u, 0)
            return s, t, u, v

        f = spmd(pipeline, mesh=mesh)
        x_local = jnp.full((1, 3), float(rank + 1))
        blocks_local = jnp.arange(n, dtype=jnp.float32).reshape(1, n) + 10 * rank
        s, t, u, v = f(x_local, blocks_local)
        s_l, t_l, u_l, v_l = (local_blocks(a) for a in (s, t, u, v))
        np.testing.assert_allclose(s_l, 3.0)          # 1 + 2
        # alltoall: rank r's block j is rank j's input block r
        expect_t = np.array([[10 * j + rank for j in range(n)]], np.float32)
        np.testing.assert_allclose(t_l, expect_t)
        np.testing.assert_allclose(u_l, 3.0)          # ring of equal values
        np.testing.assert_allclose(v_l, 3.0)          # bcast of the same
        print(f"PIPE_OK{rank}")
        """,
    )
    _assert_ok(outs, "PIPE_OK")



def test_distributed_grad_through_allreduce():
    # The data-parallel gradient identity (reference
    # test_allreduce.py:141-193) across real processes: grad of
    # sum(allreduce(x)) is 1 per element on every rank.
    outs = run_world(
        2,
        """
        mesh = world_mesh()

        def loss(x):
            return m4t.allreduce(x, op=m4t.SUM).sum()

        g = spmd(lambda x: jax.grad(loss)(x), mesh=mesh)
        val = spmd(loss, mesh=mesh)
        x_local = jnp.full((1, 4), float(rank + 1))
        gl = local_blocks(g(x_local))
        np.testing.assert_allclose(gl, 1.0)
        vl = local_blocks(val(x_local))
        np.testing.assert_allclose(vl, 4 * 3.0)
        print(f"GRAD_OK{rank}")
        """,
    )
    _assert_ok(outs, "GRAD_OK")



def test_distributed_ordering_deep_chain():
    # Ten dependent collectives in program order, twice (two separate
    # jit programs): exercises the value-token ordering chain and
    # channel-id determinism across a *sequence* of compilations.
    outs = run_world(
        2,
        """
        n = nprocs
        mesh = world_mesh()
        ring_dst = tuple((r + 1) % n for r in range(n))
        ring_src = tuple((r - 1) % n for r in range(n))

        def chain(x):
            for _ in range(5):
                x = m4t.allreduce(x, op=m4t.SUM) / n
                x = m4t.sendrecv(x, x, ring_src, ring_dst)
            return x

        f = spmd(chain, mesh=mesh)
        x_local = jnp.full((1, 2), float(rank))
        out1 = local_blocks(f(x_local))
        out2 = local_blocks(f(x_local + 1))
        # mean preserved by allreduce/n; ring of equal values is identity
        np.testing.assert_allclose(out1, 0.5)
        np.testing.assert_allclose(out2, 1.5)
        print(f"CHAIN_OK{rank}")
        """,
    )
    _assert_ok(outs, "CHAIN_OK")


def test_distributed_iterate_outputs():
    # The donate-and-iterate pattern: feeding a previous spmd output
    # (a global array with non-addressable shards) back into the next
    # call must pass through without a host round-trip.
    outs = run_world(
        2,
        """
        mesh = world_mesh()
        f = spmd(lambda x: m4t.allreduce(x, op=m4t.SUM) / nprocs, mesh=mesh)
        state = jnp.full((1, 3), float(rank + 1))
        for _ in range(4):
            state = f(state)   # global jax.Array fed straight back in
        np.testing.assert_allclose(local_blocks(state), 1.5)  # mean fixpoint
        print(f"ITER_OK{rank}")
        """,
    )
    _assert_ok(outs, "ITER_OK")
