"""Pallas RDMA ring all-reduce, validated in interpret mode on the
virtual CPU mesh against the HLO AllReduce result."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu.ops.pallas_ring import ring_allreduce

N = 8


@pytest.mark.parametrize("shape", [(N * 128 * 8,), (333,), (4, 1000)])
def test_ring_allreduce_matches_psum(run_spmd, per_rank, shape):
    rng = np.random.RandomState(0)
    arr = np.stack(
        [rng.randn(*shape).astype(np.float32) for _ in range(N)]
    )

    out = run_spmd(
        lambda x: ring_allreduce(x, "ranks", N, interpret=True), jnp.asarray(arr)
    )
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-5)


def test_ring_allreduce_size1():
    x = jnp.arange(5.0)
    np.testing.assert_allclose(
        ring_allreduce(x, "ranks", 1, interpret=True), x
    )
