"""Pallas RDMA ring all-reduce, validated in interpret mode on the
virtual CPU mesh against the HLO AllReduce result."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4t
from mpi4jax_tpu.ops.pallas_ring import ring_allreduce

from tests.conftest import needs_supported_jax

# jax<0.6 cannot lower these kernels (lax.platform_dependent
# concretizes under interpret mode; Pallas API drift) — skip the
# module below the supported floor instead of failing as false alarms
pytestmark = needs_supported_jax

N = 8


@pytest.mark.parametrize("shape", [(N * 128 * 8,), (333,), (4, 1000)])
def test_ring_allreduce_matches_psum(run_spmd, per_rank, shape):
    rng = np.random.RandomState(0)
    arr = np.stack(
        [rng.randn(*shape).astype(np.float32) for _ in range(N)]
    )

    out = run_spmd(
        lambda x: ring_allreduce(x, "ranks", N, interpret=True), jnp.asarray(arr)
    )
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-5)


def test_ring_allreduce_size1():
    x = jnp.arange(5.0)
    np.testing.assert_allclose(
        ring_allreduce(x, "ranks", 1, interpret=True), x
    )


def test_ring_allreduce_streamed_64mib(run_spmd):
    # 64 MiB payload exceeds the VMEM-resident budget -> grid-streamed
    # variant (multiple macro-blocks). Values chosen so the f32 sum is
    # exact; compare block boundaries and a random sample.
    total = (64 << 20) // 4  # 16M f32 elements
    base = np.arange(total, dtype=np.float32) % 1024
    arr = np.stack([base + r for r in range(N)])

    out = run_spmd(
        lambda x: ring_allreduce(x, "ranks", N, interpret=True),
        jnp.asarray(arr),
    )
    expected = base * N + sum(range(N))
    idx = np.concatenate(
        [np.arange(2048), np.arange(total - 2048, total),
         np.random.RandomState(1).randint(0, total, 4096)]
    )
    for r in range(N):
        np.testing.assert_allclose(out[r][idx], expected[idx], rtol=1e-6)


def test_ring_allreduce_bf16_f32_accumulation(run_spmd):
    # bf16 payloads accumulate in f32: summing 8 copies of 1/256 stays
    # exact (bf16 accumulation would lose low bits against big values).
    arr = np.stack(
        [np.full(2048, 1.0 / 256, np.float32) + (512.0 if r == 0 else 0.0)
         for r in range(N)]
    ).astype(jnp.bfloat16)

    out = run_spmd(
        lambda x: ring_allreduce(x, "ranks", N, interpret=True),
        jnp.asarray(arr),
    )
    # f32 accumulation: 512 + 8/256 = 512.03125; each hop rounds the
    # partial to bf16, so tolerance is bf16 ulp at 512 (= 2.0)
    expected = 512.0 + N / 256
    assert abs(float(np.asarray(out[0].astype(np.float32))[0]) - expected) <= 2.0


def test_ring_allreduce_tpu_compile_check():
    # Cross-platform export validates the Mosaic TPU lowering of the
    # compiled-mode path (semaphore protocol included) without a chip.
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from mpi4jax_tpu.parallel import world_mesh

    mesh = world_mesh()

    fn = jax.jit(
        shard_map(
            lambda x: ring_allreduce(
                x.reshape(x.shape[1:]), "ranks", N, interpret=False
            )[None],
            mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
            check_vma=False,
        )
    )
    x = jnp.ones((N, 512 * 128), jnp.float32)
    try:
        exported = jax.export.export(fn, platforms=["tpu"])(x)
    except Exception as e:  # pragma: no cover - surface the real error
        pytest.fail(f"TPU lowering of the compiled ring failed: {e}")
    assert "tpu_custom_call" in exported.mlir_module()


def test_ring_reduce_scatter_matches_psum_scatter(run_spmd):
    from mpi4jax_tpu.ops.pallas_ring_parts import ring_reduce_scatter

    rng = np.random.RandomState(5)
    arr = rng.randn(N, N, 300).astype(np.float32)  # per-rank (N, 300)

    out = run_spmd(
        lambda x: ring_reduce_scatter(x, "ranks", N, interpret=True),
        jnp.asarray(arr),
    )
    expected = arr.sum(axis=0)  # (N, 300): block r = sum over ranks
    for r in range(N):
        np.testing.assert_allclose(out[r], expected[r], rtol=1e-5, atol=1e-5)


def test_ring_allgather_matches_all_gather(run_spmd):
    from mpi4jax_tpu.ops.pallas_ring_parts import ring_allgather

    rng = np.random.RandomState(6)
    arr = rng.randn(N, 4, 77).astype(np.float32)

    out = run_spmd(
        lambda x: ring_allgather(x, "ranks", N, interpret=True),
        jnp.asarray(arr),
    )
    for r in range(N):
        np.testing.assert_array_equal(np.asarray(out[r]), arr)


def test_ring_parts_tpu_compile_check():
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from mpi4jax_tpu.ops.pallas_ring_parts import (
        ring_allgather,
        ring_reduce_scatter,
    )
    from mpi4jax_tpu.parallel import world_mesh

    mesh = world_mesh()

    def body(x):
        # derived (not explicit) collective ids: the ZeRO composition
        # must get distinct ids per kernel kind — a shared id aliases
        # the barrier semaphores and wedges the Mosaic compile
        rs = ring_reduce_scatter(x.reshape(x.shape[1:]), "ranks", N)
        ag = ring_allgather(rs, "ranks", N)
        return ag[None]

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    ))
    x = jnp.ones((N, N, 128 * 16), jnp.float32)
    try:
        exported = jax.export.export(fn, platforms=["tpu"])(x)
    except Exception as e:  # pragma: no cover
        pytest.fail(f"TPU lowering of ring parts failed: {e}")
    assert exported.mlir_module().count("tpu_custom_call") >= 2


def test_ring_allreduce_streamed_tpu_compile_check():
    # The grid-streamed variant (multiple macro-blocks, cross-block
    # credit carries, first-block barrier, last-block drain) and the
    # bf16 wire path must lower through Mosaic too — the VMEM-resident
    # f32 check above does not exercise either.
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from mpi4jax_tpu.parallel import world_mesh

    mesh = world_mesh()
    # ~24 MiB f32 payload -> multiple grid blocks under the 6 MiB budget
    big = (24 << 20) // 4
    fn = jax.jit(shard_map(
        lambda x: ring_allreduce(x.reshape(x.shape[1:]), "ranks", N)[None],
        mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    ))
    x = jnp.ones((N, big), jnp.float32)
    exported = jax.export.export(fn, platforms=["tpu"])(x)
    assert "tpu_custom_call" in exported.mlir_module()

    xb = jnp.ones((N, (4 << 20) // 2), jnp.bfloat16)  # bf16 wire path
    fnb = jax.jit(shard_map(
        lambda x: ring_allreduce(x.reshape(x.shape[1:]), "ranks", N)[None],
        mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=False,
    ))
    exported_b = jax.export.export(fnb, platforms=["tpu"])(xb)
    assert "tpu_custom_call" in exported_b.mlir_module()


# ---------------------------------------------------------------------------
# ring_guard: compiled-mode safety net + platform-derived routing
# ---------------------------------------------------------------------------


def test_ring_guard_probe_plumbing():
    # Success / failure / watchdog paths of the probe runner, exercised
    # on CPU with injected child sources (the real probe source needs
    # >= 2 TPU chips, which this environment never has).
    from mpi4jax_tpu.ops import ring_guard

    assert ring_guard._run_probe(src="print('RING_PROBE_OK')") is True
    with pytest.warns(RuntimeWarning, match="probe failed"):
        assert ring_guard._run_probe(src="raise SystemExit(3)") is False
    with pytest.warns(RuntimeWarning, match="timed out"):
        assert (
            ring_guard._run_probe(timeout_s=2, src="import time; time.sleep(60)")
            is False
        )


def test_ring_guard_memoized_fallback(monkeypatch):
    # A failed probe pins the process to the HLO path without re-probing.
    from mpi4jax_tpu.ops import ring_guard

    calls = []
    monkeypatch.setattr(
        ring_guard, "_run_probe", lambda *a, **k: (calls.append(1), False)[1]
    )
    monkeypatch.setattr(ring_guard, "_probe_result", None)
    assert ring_guard.compiled_ring_healthy() is False
    assert ring_guard.compiled_ring_healthy() is False
    assert len(calls) == 1


def test_ring_guard_noprobe_env(monkeypatch):
    from mpi4jax_tpu.ops import ring_guard

    monkeypatch.setenv("MPI4JAX_TPU_RING_NOPROBE", "1")
    monkeypatch.setattr(ring_guard, "_probe_result", None)
    monkeypatch.setattr(
        ring_guard,
        "_run_probe",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("probe ran")),
    )
    assert ring_guard.compiled_ring_healthy() is True


def test_routed_ring_interpret_on_cpu(run_spmd):
    # On a CPU lowering, routed_ring must select the interpret branch
    # (platform_dependent default) and produce the allreduce result.
    from mpi4jax_tpu.ops.ring_guard import routed_ring

    arr = np.stack(
        [np.full(N * 128 * 8, float(r + 1), np.float32) for r in range(N)]
    )
    out = run_spmd(
        lambda x: routed_ring(ring_allreduce, x, "ranks", N), jnp.asarray(arr)
    )
    expected = arr.sum(axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


def test_routed_ring_tpu_export_gets_compiled_kernel():
    # Under cross-platform export to TPU from this CPU host, the
    # platform-dependent routing must lower the *compiled* Mosaic
    # kernel — the exact case the default_backend() heuristic got
    # wrong (it would have baked interpret mode into a TPU program).
    from jax.sharding import Mesh, PartitionSpec as P

    from mpi4jax_tpu.ops.ring_guard import routed_ring

    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("r",))
    body = lambda v: routed_ring(ring_allreduce, v, "r", n)
    fn = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P("r"), out_specs=P("r"), check_vma=False
        )
    )
    x = jnp.zeros((n * 8 * 128,), jnp.float32)
    exported = jax.export.export(fn, platforms=["tpu"])(x)
    assert "tpu_custom_call" in exported.mlir_module()


def test_ring_guard_inconclusive_probe_keeps_ring():
    # A probe that cannot reach the hardware at all (chip locked by the
    # parent, single device) is inconclusive: the opt-in compiled ring
    # stays available, with an "unvalidated" warning.
    from mpi4jax_tpu.ops import ring_guard

    with pytest.warns(RuntimeWarning, match="UNVALIDATED"):
        assert (
            ring_guard._run_probe(
                src="print('RING_PROBE_INAPPLICABLE device locked')"
            )
            is True
        )
