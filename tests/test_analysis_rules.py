"""Rule registry: each seeded-bad fixture is flagged with the correct
M4T rule code and a source location, clean programs lint with zero
findings, and the emit-time hook (M4T_STATIC_CHECK) screens the
site-local subset."""

import warnings

import jax
import jax.numpy as jnp
import pytest
from jax import lax

import mpi4jax_tpu as m4t
from mpi4jax_tpu.analysis import LintConfig, RULES, lint
from mpi4jax_tpu.analysis.emit_check import (
    M4TStaticCheckWarning,
    StaticCheckError,
    reset_seen,
)

N = 8
X = jnp.zeros((4,), jnp.float32)
RING_DEST = [(r + 1) % N for r in range(N)]
RING_SRC = [(r - 1) % N for r in range(N)]


def codes(report):
    return sorted({f.code for f in report.findings})


def test_rule_catalog_is_complete():
    assert list(RULES) == [
        "M4T101",
        "M4T102",
        "M4T103",
        "M4T104",
        "M4T105",
        "M4T106",
    ]


# -- M4T101: rank-divergent control flow ------------------------------


def test_m4t101_rank_divergent_cond_around_allreduce():
    def bad(x):
        r = lax.axis_index("ranks")
        return lax.cond(r == 0, lambda v: m4t.allreduce(v), lambda v: v, x)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert "M4T101" in codes(rep)
    (f,) = [f for f in rep.findings if f.code == "M4T101"]
    assert f.severity == "error"
    assert "test_analysis_rules.py" in f.message  # names the cond line
    assert f.site is not None and f.site.op == "AllReduce"


def test_m4t101_rank_divergent_while():
    def bad(x):
        r = lax.axis_index("ranks")

        def cond(state):
            v, it = state
            return it < r  # per-rank trip count

        def body(state):
            v, it = state
            return m4t.allreduce(v), it + 1

        v, _ = lax.while_loop(cond, body, (x, jnp.asarray(0, jnp.int32)))
        return v

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert "M4T101" in codes(rep)


def test_m4t101_not_fired_for_uniform_predicate():
    def ok(x):
        s = x.sum()  # data-dependent but rank-free dataflow
        return lax.cond(
            s > 0, lambda v: m4t.allreduce(v), lambda v: m4t.allreduce(v), x
        )

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert "M4T101" not in codes(rep)


# -- M4T102: branch-sequence mismatch ---------------------------------


def test_m4t102_branch_sequence_mismatch():
    def bad(x):
        # data-dependent (not rank-derived) predicate, diverging
        # collective sequences: allgather vs allreduce
        return lax.cond(
            x.sum() > 0,
            lambda v: m4t.allreduce(v),
            lambda v: m4t.allgather(v)[0],
            x,
        )

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T102"]
    (f,) = rep.findings
    assert "AllReduce" in f.message and "AllGather" in f.message
    assert "test_analysis_rules.py" in f.message


def test_m4t102_matching_branches_clean():
    def ok(x):
        return lax.cond(
            x.sum() > 0,
            lambda v: m4t.allreduce(v),
            lambda v: m4t.allreduce(v * 2),
            x,
        )

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert rep.findings == []


# -- M4T103: unpaired / self-deadlocking send-recv --------------------


def test_m4t103_unpaired_ring_send():
    def bad(x):
        m4t.send(x, RING_DEST, tag=5)
        return x  # no recv: the transfer is silently never emitted

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T103"]
    (f,) = rep.findings
    assert "tag=5" in f.message and "never matched" in f.message


def test_m4t103_self_edge_ring():
    def bad(x):
        # shift arithmetic gone degenerate: (r + N) % N == r
        table = [(r + N) % N for r in range(N)]
        return m4t.sendrecv(x, x, table, table)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T103"]
    (f,) = rep.findings
    assert "self-edges" in f.message
    assert f.site is not None and "test_analysis_rules.py" in f.site.source


def test_m4t103_mirror_mismatch_trace_error_becomes_finding():
    def bad(x):
        bad_src = [(r + 1) % N for r in range(N)]  # should be -1 ring
        return m4t.sendrecv(x, x, bad_src, RING_DEST)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T103"]
    assert rep.error is None


def test_m4t103_proper_ring_clean():
    def ok(x):
        m4t.send(x, RING_DEST, tag=1)
        return m4t.recv(x, RING_SRC, tag=1)

    rep = lint(ok, (X,), axis_env={"ranks": N})
    assert rep.findings == []
    assert [s.op for s in rep.sites] == ["CollectivePermute"]


# -- M4T104: token discipline -----------------------------------------


def test_m4t104_direct_bind_bypasses_token_chain():
    from mpi4jax_tpu.comm import BoundComm, SUM
    from mpi4jax_tpu.ops.allreduce import mpi_allreduce_p

    def bad(x):
        bound = BoundComm(axes=("ranks",), size=N)
        return mpi_allreduce_p.bind(x, op=SUM, comm=bound, transpose=False)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T104"]
    assert "optimization_barrier" in rep.findings[0].message


def test_m4t104_emitted_ops_are_tied():
    rep = lint(lambda x: m4t.allreduce(x), (X,), axis_env={"ranks": N})
    assert rep.findings == []
    assert rep.sites[0].token_tied


# -- M4T105: collective over a non-mesh axis --------------------------


def test_m4t105_vmap_over_non_mesh_axis():
    def inner(x):
        return m4t.allreduce(x, comm=m4t.Comm("batch"))

    rep = lint(
        jax.vmap(inner, axis_name="batch"),
        (jnp.zeros((3, 4), jnp.float32),),
        axis_env={"ranks": N},
    )
    assert codes(rep) == ["M4T105"]
    (f,) = rep.findings
    assert f.severity == "warning"
    assert "batch" in f.message


def test_m4t105_declared_axis_is_fine():
    def inner(x):
        return m4t.allreduce(x, comm=m4t.Comm("batch"))

    rep = lint(
        jax.vmap(inner, axis_name="batch"),
        (jnp.zeros((3, 4), jnp.float32),),
        axis_env={"ranks": N, "batch": 3},
    )
    assert rep.findings == []


# -- M4T106: reduction dtype hazards ----------------------------------


def test_m4t106_bf16_psum():
    def bad(x):
        return m4t.allreduce(x.astype(jnp.bfloat16))

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T106"]
    (f,) = rep.findings
    assert f.severity == "warning"
    assert "bfloat16" in f.message
    assert f.site is not None and "test_analysis_rules.py" in f.site.source


def test_m4t106_int8_sum_overflow():
    def bad(x):
        return m4t.allreduce(x.astype(jnp.int8))

    rep = lint(bad, (X,), axis_env={"ranks": N})
    assert codes(rep) == ["M4T106"]


def test_m4t106_threshold_config():
    def f(x):
        return m4t.allreduce(x.astype(jnp.bfloat16))

    rep = lint(
        f,
        (X,),
        axis_env={"ranks": N},
        config=LintConfig(low_precision_world=16),
    )
    assert rep.findings == []


def test_m4t106_max_min_not_flagged():
    # only SUM accumulates error; MAX/MIN are exact in any dtype
    def f(x):
        return m4t.allreduce(x.astype(jnp.bfloat16), op=m4t.MAX)

    rep = lint(f, (X,), axis_env={"ranks": N})
    assert rep.findings == []


# -- disabled rules, report plumbing ----------------------------------


def test_rule_disable():
    def bad(x):
        return m4t.allreduce(x.astype(jnp.bfloat16))

    rep = lint(
        bad,
        (X,),
        axis_env={"ranks": N},
        config=LintConfig(disabled=frozenset({"M4T106"})),
    )
    assert rep.findings == []


def test_report_json_schema_fields():
    def bad(x):
        r = lax.axis_index("ranks")
        return lax.cond(r == 0, lambda v: m4t.allreduce(v), lambda v: v, x)

    rep = lint(bad, (X,), axis_env={"ranks": N})
    js = rep.to_json()
    assert js["version"] == 1
    assert js["axis_env"] == {"ranks": N}
    assert js["n_sites"] == len(js["sites"]) == 1
    site = js["sites"][0]
    for key in (
        "index", "prim", "op", "shape", "dtype", "bytes", "axes",
        "world", "path", "source", "fingerprint", "token_tied",
    ):
        assert key in site
    finding = js["findings"][0]
    for key in ("code", "severity", "message", "source", "sites"):
        assert key in finding


def test_untraceable_function_reports_error_not_crash():
    def broken(x):
        raise ValueError("unrelated user bug")

    rep = lint(broken, (X,), axis_env={"ranks": N})
    assert rep.error is not None and "unrelated user bug" in rep.error
    assert rep.findings == []
    assert not rep.clean


# -- the emit-time hook (M4T_STATIC_CHECK) ----------------------------


@pytest.fixture()
def static_check_mode(monkeypatch):
    from mpi4jax_tpu import config

    def set_mode(mode):
        monkeypatch.setattr(config, "STATIC_CHECK", mode)
        reset_seen()

    yield set_mode
    reset_seen()


@pytest.mark.telemetry
def test_emit_check_warns_on_bf16_sum(static_check_mode):
    static_check_mode("warn")
    with pytest.warns(M4TStaticCheckWarning, match="M4T106"):
        jax.make_jaxpr(
            lambda x: m4t.allreduce(x), axis_env=[("ranks", N)]
        )(jnp.zeros((4,), jnp.bfloat16))


@pytest.mark.telemetry
def test_emit_check_warns_once_per_site(static_check_mode):
    static_check_mode("warn")

    def trace():
        return jax.make_jaxpr(
            lambda x: m4t.allreduce(x), axis_env=[("ranks", N)]
        )(jnp.zeros((4,), jnp.bfloat16))

    with pytest.warns(M4TStaticCheckWarning):
        trace()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a second warning would raise
        trace()


@pytest.mark.telemetry
def test_emit_check_error_mode_raises_at_trace(static_check_mode):
    static_check_mode("error")
    with pytest.raises(StaticCheckError, match="M4T106"):
        jax.make_jaxpr(
            lambda x: m4t.allreduce(x), axis_env=[("ranks", N)]
        )(jnp.zeros((4,), jnp.bfloat16))


@pytest.mark.telemetry
def test_emit_check_self_edge(static_check_mode):
    static_check_mode("warn")
    table = list(range(N))
    with pytest.warns(M4TStaticCheckWarning, match="M4T103"):
        jax.make_jaxpr(
            lambda x: m4t.sendrecv(x, x, table, table),
            axis_env=[("ranks", N)],
        )(X)


@pytest.mark.telemetry
def test_emit_check_off_by_default():
    from mpi4jax_tpu import config

    assert config.STATIC_CHECK == ""
    with warnings.catch_warnings():
        warnings.simplefilter("error", M4TStaticCheckWarning)
        jax.make_jaxpr(
            lambda x: m4t.allreduce(x), axis_env=[("ranks", N)]
        )(jnp.zeros((4,), jnp.bfloat16))
