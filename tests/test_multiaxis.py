"""Multi-axis communicators: psum-family ops over a Comm spanning a
2-D mesh's axes (the flat COMM_WORLD view of a (dp, tp) mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import mpi4jax_tpu as m4t


@pytest.fixture(scope="module")
def mesh2d():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("a", "b"))


def run2d(mesh2d, fn, stacked):
    body = lambda x: jax.tree.map(
        lambda o: o.reshape((1, 1) + o.shape), fn(x.reshape(x.shape[2:]))
    )
    out = jax.jit(
        shard_map(
            body, mesh=mesh2d, in_specs=P("a", "b"), out_specs=P("a", "b"),
            check_vma=False,
        )
    )(stacked)
    return jax.tree.map(np.asarray, out)


def test_multiaxis_allreduce(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1)
    out = run2d(mesh2d, lambda x: m4t.allreduce(x, op=m4t.SUM, comm=comm), jnp.asarray(arr))
    np.testing.assert_allclose(out.ravel(), np.full(8, 28.0))


def test_multiaxis_rank_and_size(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.zeros((2, 4, 1), np.float32)
    out = run2d(
        mesh2d,
        lambda x: x + comm.Get_rank().astype(jnp.float32) + 10.0 * comm.Get_size(),
        jnp.asarray(arr),
    )
    np.testing.assert_allclose(out.ravel(), 80.0 + np.arange(8.0))


def test_multiaxis_bcast_and_reduce(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1) + 1

    def f(x):
        b = m4t.bcast(x, 5, comm=comm)
        r = m4t.reduce(x, m4t.SUM, 0, comm=comm)
        return b, r

    b, r = run2d(mesh2d, f, jnp.asarray(arr))
    np.testing.assert_allclose(b.ravel(), np.full(8, 6.0))
    assert r.ravel()[0] == 36.0  # root gets the sum
    np.testing.assert_allclose(r.ravel()[1:], arr.ravel()[1:])  # others keep input


def test_multiaxis_allgather_generic_op(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1) + 1
    out = run2d(mesh2d, lambda x: m4t.allreduce(x, op=m4t.PROD, comm=comm), jnp.asarray(arr))
    np.testing.assert_allclose(out.ravel(), np.full(8, np.prod(np.arange(1.0, 9.0))))


def test_multiaxis_sendrecv_ring(mesh2d):
    # p2p over the linearized (a, b) rank space: ring shift by +1.
    comm = m4t.Comm(("a", "b"))
    n = 8
    dest = tuple((r + 1) % n for r in range(n))
    source = tuple((r - 1) % n for r in range(n))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1)
    out = run2d(
        mesh2d,
        lambda x: m4t.sendrecv(x, x, source, dest, comm=comm),
        jnp.asarray(arr),
    )
    np.testing.assert_allclose(out.ravel(), (np.arange(8.0) - 1) % 8)


def test_multiaxis_alltoall(mesh2d):
    comm = m4t.Comm(("a", "b"))
    # rank r's block j = 10*r + j; after alltoall rank r's block j = 10*j + r
    arr = np.asarray(
        [[10.0 * r + j for j in range(8)] for r in range(8)], np.float32
    ).reshape(2, 4, 8, 1)
    out = run2d(mesh2d, lambda x: m4t.alltoall(x, comm=comm), jnp.asarray(arr))
    expect = np.asarray([[10.0 * j + r for j in range(8)] for r in range(8)])
    np.testing.assert_allclose(out.reshape(8, 8), expect)


def test_multiaxis_scan(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1)
    out = run2d(mesh2d, lambda x: m4t.scan(x, m4t.SUM, comm=comm), jnp.asarray(arr))
    np.testing.assert_allclose(out.ravel(), np.cumsum(np.arange(8.0)))


def test_multiaxis_scatter(mesh2d):
    comm = m4t.Comm(("a", "b"))
    root = 3
    # per-rank (8,) inputs; only root's values matter
    arr = np.asarray(
        [100.0 * r + np.arange(8.0) for r in range(8)], np.float32
    ).reshape(2, 4, 8)
    out = run2d(
        mesh2d, lambda x: m4t.scatter(x, root, comm=comm), jnp.asarray(arr)
    )
    np.testing.assert_allclose(out.ravel(), 100.0 * root + np.arange(8.0))


def test_multiaxis_reduce_scatter(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.asarray(
        [r + np.arange(8.0) for r in range(8)], np.float32
    ).reshape(2, 4, 8)
    out = run2d(
        mesh2d, lambda x: m4t.reduce_scatter(x, m4t.SUM, comm=comm), jnp.asarray(arr)
    )
    # rank r gets sum_ranks (rank + r) = 28 + 8r
    np.testing.assert_allclose(out.ravel(), 28.0 + 8.0 * np.arange(8.0))


def test_multiaxis_allgather(mesh2d):
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1)
    out = run2d(mesh2d, lambda x: m4t.allgather(x, comm=comm), jnp.asarray(arr))
    np.testing.assert_allclose(out.reshape(8, 8), np.tile(np.arange(8.0)[None, :, None], (8, 1, 1)).reshape(8, 8))

def test_multiaxis_grad_through_allreduce(mesh2d):
    # AD parity holds over multi-axis comms: grad of sum-allreduce(x^2)
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(8.0, dtype=np.float32).reshape(2, 4, 1)

    def f(x):
        return jax.grad(
            lambda v: m4t.allreduce((v ** 2).sum(), op=m4t.SUM, comm=comm)
        )(x)

    out = run2d(mesh2d, f, jnp.asarray(arr))
    np.testing.assert_allclose(out.ravel(), 2 * np.arange(8.0))


def test_multiaxis_alltoall_grad(mesh2d):
    # alltoall transpose rule over the linearized 2-D comm
    comm = m4t.Comm(("a", "b"))
    arr = np.arange(64.0, dtype=np.float32).reshape(2, 4, 8)

    def f(x):
        return jax.grad(
            lambda v: (m4t.alltoall(v, comm=comm) * v).sum()
        )(x)

    out = run2d(mesh2d, f, jnp.asarray(arr))
    assert np.isfinite(out).all()
    # numeric check: loss = sum_j y_r[j] * x_r[j] with y_r[j] = x_j[r];
    # d/dx_r[j] = y_r[j] + (x_r transported back) = x_j[r] + x_j[r]
    x = arr.reshape(8, 8)
    expect = np.stack([2 * x[:, r] for r in range(8)])
    np.testing.assert_allclose(out.reshape(8, 8), expect)


def test_multiaxis_quantized_allreduce(mesh2d):
    comm = m4t.Comm(("a", "b"))
    rng = np.random.RandomState(7)
    arr = rng.randn(8, 2048).astype(np.float32).reshape(2, 4, 2048)
    out = run2d(
        mesh2d,
        lambda x: m4t.quantized_allreduce(x, comm=comm),
        jnp.asarray(arr),
    )
    # same accuracy contract as the single-axis tests
    # (tests/test_quantized.py): max error below 5% of the result scale
    expected = arr.reshape(8, 2048).sum(axis=0)
    scale = np.abs(expected).max()
    for r in range(8):
        err = np.abs(out.reshape(8, 2048)[r] - expected).max() / scale
        assert err < 0.05, err
