"""Deep-halo fused SPMD step (``models/fused_spmd.py``).

Two equivalence properties pin the design:

1. **vs the composable SPMD path** (f32, interpret): interiors agree
   to the stale-ghost boundary term. The composable path reproduces
   the reference's exchange placement (``shallow_water.py:270-403``),
   where rank-ghost velocity rows carry the *pre-friction* values of
   the previous step (friction updates interiors after the last
   exchange); the deep-halo exchange ships post-friction rows, so the
   paths differ by O(nu*dt) at block boundaries — small but real.
2. **vs the global single-rank trajectory** (f64, subprocess): the
   deep-halo path reads globally consistent values everywhere, so its
   reassembled solution must match the *undecomposed* solve to float
   reordering (~1e-15 scaled in f64). This is the discriminating
   check — exact decomposition invariance, a strictly stronger
   property than the reference path has — and one an exchange-width
   or offset bug cannot pass.
"""

import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import needs_supported_jax

from mpi4jax_tpu.models import fused_spmd as fsp
from mpi4jax_tpu.models.shallow_water import (
    ModelState,
    ShallowWaterConfig,
    ShallowWaterModel,
)
from mpi4jax_tpu.parallel import spmd, world_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup(n, ny=96, nx=48):
    cfg = ShallowWaterConfig(nx=nx, ny=ny, dims=(n, 1))
    model = ShallowWaterModel(cfg)
    blocks = model.initial_state_blocks()
    state = ModelState(*(jnp.asarray(b) for b in blocks))
    return cfg, model, state


@pytest.mark.parametrize("n", [2, 4, 8])
def test_interiors_match_composable(n):
    cfg, model, state = _setup(n)
    mesh = world_mesh(n)
    stepper = fsp.FusedRowDecomp(cfg, block_rows=8, interpret=True)

    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
    ref = spmd(lambda s: model.multistep(s, 4), mesh=mesh)(s1)
    fus = spmd(lambda s: stepper.multistep(s, 4), mesh=mesh)(s1)

    for name, a, b in zip(ModelState._fields, ref, fus):
        ai = np.asarray(a)[:, 1:-1, 1:-1]
        bi = np.asarray(b)[:, 1:-1, 1:-1]
        d = np.max(np.abs(ai - bi))
        scale = 1.0 + np.max(np.abs(ai))
        assert d / scale < 1e-4, (name, d)


def test_multistep_composes():
    cfg, model, state = _setup(4)
    mesh = world_mesh(4)
    stepper = fsp.FusedRowDecomp(cfg, block_rows=8, interpret=True)
    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
    once = spmd(lambda s: stepper.multistep(s, 2), mesh=mesh)(s1)
    twice = spmd(
        lambda s: stepper.multistep(stepper.multistep(s, 1), 1), mesh=mesh
    )(s1)
    for a, b in zip(once, twice):
        # interiors only: ghost rows of a returned state are unspecified
        np.testing.assert_allclose(
            np.asarray(a)[:, 1:-1, 1:-1],
            np.asarray(b)[:, 1:-1, 1:-1],
            rtol=0,
            atol=1e-6,
        )


@pytest.mark.parametrize("num_steps", [4, 5])
def test_interiors_match_composable_spp2(num_steps):
    """Temporal blocking across ranks: one radius-6 exchange per two
    steps (amortized 1 collective/step instead of 2); the odd span
    exercises the single-step remainder pass on the deep layout."""
    n = 4
    cfg, model, state = _setup(n)
    mesh = world_mesh(n)
    stepper = fsp.FusedRowDecomp(
        cfg, block_rows=8, interpret=True, steps_per_pass=2
    )
    assert stepper.spp == 2 and stepper._depth == 6

    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
    ref = spmd(lambda s: model.multistep(s, num_steps), mesh=mesh)(s1)
    fus = spmd(lambda s: stepper.multistep(s, num_steps), mesh=mesh)(s1)

    for name, a, b in zip(ModelState._fields, ref, fus):
        ai = np.asarray(a)[:, 1:-1, 1:-1]
        bi = np.asarray(b)[:, 1:-1, 1:-1]
        d = np.max(np.abs(ai - bi))
        scale = 1.0 + np.max(np.abs(ai))
        assert d / scale < 1e-4, (name, d)


def test_2d_interiors_match_composable_spp2():
    cfg = ShallowWaterConfig(nx=48, ny=96, dims=(2, 2))
    model = ShallowWaterModel(cfg)
    state = ModelState(
        *(jnp.asarray(b) for b in model.initial_state_blocks())
    )
    mesh = world_mesh(4)
    stepper = fsp.FusedDecomp2D(
        cfg, block_rows=8, interpret=True, steps_per_pass=2
    )
    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
    ref = spmd(lambda s: model.multistep(s, 4), mesh=mesh)(s1)
    fus = spmd(lambda s: stepper.multistep(s, 4), mesh=mesh)(s1)
    for name, a, b in zip(ModelState._fields, ref, fus):
        ai = np.asarray(a)[:, 1:-1, 1:-1]
        bi = np.asarray(b)[:, 1:-1, 1:-1]
        d = np.max(np.abs(ai - bi))
        scale = 1.0 + np.max(np.abs(ai))
        assert d / scale < 1e-4, (name, d)


def test_spp2_guard_rails():
    # depth-6 exchange needs >= 6 interior rows per rank
    with pytest.raises(ValueError, match="steps_per_pass=2"):
        fsp.FusedRowDecomp(
            ShallowWaterConfig(nx=48, ny=40, dims=(8, 1)),
            steps_per_pass=2,
        )


def test_guard_rails():
    with pytest.raises(NotImplementedError, match="row decomposition"):
        fsp.FusedRowDecomp(ShallowWaterConfig(nx=48, ny=96, dims=(2, 2)))
    with pytest.raises(NotImplementedError, match="periodic_x"):
        fsp.FusedRowDecomp(
            ShallowWaterConfig(nx=48, ny=96, dims=(4, 1), periodic_x=False)
        )
    with pytest.raises(ValueError, match="interior rows per rank"):
        fsp.FusedRowDecomp(ShallowWaterConfig(nx=48, ny=8, dims=(8, 1)))
    with pytest.raises(ValueError, match="no legal block size"):
        fsp.FusedRowDecomp(
            ShallowWaterConfig(nx=48, ny=32, dims=(4, 1)), block_rows=8
        )


_F64_SCRIPT = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import sys
sys.path.insert(0, {repo!r})
import jax.numpy as jnp
import numpy as np

from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models.fused_spmd import FusedRowDecomp
from mpi4jax_tpu.parallel import spmd, world_mesh

N = 4
cfg = ShallowWaterConfig(nx=48, ny=96, dims=(N, 1), dtype=np.float64)
gcfg = ShallowWaterConfig(nx=48, ny=96, dims=(1, 1), dtype=np.float64)
model = ShallowWaterModel(cfg)
gmodel = ShallowWaterModel(gcfg)
mesh = world_mesh(N)

state0 = ModelState(
    *(jnp.asarray(b, jnp.float64) for b in model.initial_state_blocks())
)
g = ModelState(
    *(jnp.asarray(b[0], jnp.float64) for b in gmodel.initial_state_blocks())
)

s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state0)
stepper = FusedRowDecomp(cfg, block_rows=8, interpret=True)
fus = spmd(lambda s: stepper.multistep(s, 8), mesh=mesh)(s1)

g = gmodel.step(g, first_step=True)
for _ in range(8):
    g = gmodel.step(g)

worst = 0.0
for blk, want in zip(fus, g):
    got = ShallowWaterModel.reassemble(np.asarray(blk), (N, 1))
    ref = np.asarray(want)[1:-1, 1:-1]
    d = np.max(np.abs(got - ref))
    worst = max(worst, d / (1.0 + np.max(np.abs(ref))))
assert worst < 1e-12, f"not decomposition-invariant: {{worst:.3e}}"

# temporally blocked (spp=2): the deep radius-6 exchange must preserve
# the same exactness vs the undecomposed global solve
stepper2 = FusedRowDecomp(cfg, block_rows=8, interpret=True,
                          steps_per_pass=2)
fus2 = spmd(lambda s: stepper2.multistep(s, 8), mesh=mesh)(s1)
worst2 = 0.0
for blk, want in zip(fus2, g):
    got = ShallowWaterModel.reassemble(np.asarray(blk), (N, 1))
    ref = np.asarray(want)[1:-1, 1:-1]
    d = np.max(np.abs(got - ref))
    worst2 = max(worst2, d / (1.0 + np.max(np.abs(ref))))
assert worst2 < 1e-12, f"spp=2 not decomposition-invariant: {{worst2:.3e}}"
print(f"f64 worst scaled diff vs global solve: {{worst:.3e}} "
      f"(spp=2: {{worst2:.3e}})")
"""


def test_decomposition_invariance_f64_subprocess():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(_F64_SCRIPT.format(repo=REPO))],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "worst scaled diff" in proc.stdout


# -- 2-D decomposition (FusedDecomp2D) ---------------------------------


@pytest.mark.parametrize("dims", [(2, 2), (2, 4), (1, 4)])
def test_2d_interiors_match_composable(dims):
    n = dims[0] * dims[1]
    cfg = ShallowWaterConfig(nx=48, ny=96, dims=dims)
    model = ShallowWaterModel(cfg)
    state = ModelState(
        *(jnp.asarray(b) for b in model.initial_state_blocks())
    )
    mesh = world_mesh(n)
    stepper = fsp.FusedDecomp2D(cfg, block_rows=8, interpret=True)

    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state)
    ref = spmd(lambda s: model.multistep(s, 4), mesh=mesh)(s1)
    fus = spmd(lambda s: stepper.multistep(s, 4), mesh=mesh)(s1)

    for name, a, b in zip(ModelState._fields, ref, fus):
        ai = np.asarray(a)[:, 1:-1, 1:-1]
        bi = np.asarray(b)[:, 1:-1, 1:-1]
        d = np.max(np.abs(ai - bi))
        scale = 1.0 + np.max(np.abs(ai))
        # both ghost-semantics deviations are O(nu*dt) boundary terms
        assert d / scale < 1e-4, (name, d)


def test_2d_guard_rails():
    with pytest.raises(NotImplementedError, match="periodic_x"):
        fsp.FusedDecomp2D(
            ShallowWaterConfig(nx=48, ny=96, dims=(2, 2), periodic_x=False)
        )
    with pytest.raises(ValueError, match="interior rows and columns"):
        fsp.FusedDecomp2D(ShallowWaterConfig(nx=8, ny=96, dims=(2, 4)))


_F64_2D_SCRIPT = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import sys
sys.path.insert(0, {repo!r})
import jax.numpy as jnp
import numpy as np

from mpi4jax_tpu.models.shallow_water import (
    ModelState, ShallowWaterConfig, ShallowWaterModel,
)
from mpi4jax_tpu.models.fused_spmd import FusedDecomp2D
from mpi4jax_tpu.parallel import spmd, world_mesh

def run(dims, spp=1):
    N = dims[0] * dims[1]
    cfg = ShallowWaterConfig(nx=48, ny=96, dims=dims, dtype=np.float64)
    model = ShallowWaterModel(cfg)
    state0 = ModelState(
        *(jnp.asarray(b, jnp.float64) for b in model.initial_state_blocks())
    )
    stepper = FusedDecomp2D(cfg, block_rows=8, interpret=True,
                            steps_per_pass=spp)
    if N == 1:
        s1 = jax.jit(lambda s: model.step(s, first_step=True))(
            ModelState(*(b[0] for b in state0))
        )
        fus = jax.jit(lambda s: stepper.multistep(s, 8))(s1)
        return tuple(np.asarray(f)[1:-1, 1:-1] for f in fus[:3])
    mesh = world_mesh(N)
    s1 = spmd(lambda s: model.step(s, first_step=True), mesh=mesh)(state0)
    fus = spmd(lambda s: stepper.multistep(s, 8), mesh=mesh)(s1)
    return tuple(
        ShallowWaterModel.reassemble(np.asarray(b), dims) for b in fus[:3]
    )

base = run((1, 1))
for dims in [(2, 4), (2, 2)]:
    got = run(dims)
    for a, b in zip(base, got):
        assert np.array_equal(a, b), (
            f"{{dims}}: not bit-exactly decomposition-invariant "
            f"(max dev {{np.max(np.abs(a - b)):.3e}})"
        )
    print(f"{{dims}}: bit-exact vs (1,1)")

# temporal blocking preserves decomposition invariance *within* the
# spp=2 family (same program per rank, translation-invariant), and
# tracks the spp=1 trajectory to f64 reordering noise (different
# compiled programs may reassociate — bit-exactness across programs
# is not promised, ~1e-14 over 8 steps observed)
base2 = run((1, 1), spp=2)
for a, b in zip(base, base2):
    d = np.max(np.abs(a - b)) / (1.0 + np.max(np.abs(a)))
    assert d < 1e-12, f"spp=2 diverges from spp=1: {{d:.3e}}"
for dims in [(2, 4), (2, 2)]:
    got = run(dims, spp=2)
    for a, b in zip(base2, got):
        assert np.array_equal(a, b), (
            f"{{dims}} spp=2: not bit-exactly decomposition-invariant "
            f"(max dev {{np.max(np.abs(a - b)):.3e}})"
        )
    print(f"{{dims}} spp=2: bit-exact vs (1,1) spp=2")

# and the documented seam-semantics deviation vs the reference wrap
# solve stays a small boundary term (post- vs pre-friction ghost copy,
# O(nu*dt)), identical for every decomposition
gcfg = ShallowWaterConfig(nx=48, ny=96, dims=(1, 1), dtype=np.float64)
gmodel = ShallowWaterModel(gcfg)
g = ModelState(
    *(jnp.asarray(b[0], jnp.float64) for b in gmodel.initial_state_blocks())
)
g = gmodel.step(g, first_step=True)
for _ in range(8):
    g = gmodel.step(g)
worst = 0.0
for a, want in zip(base, g):
    ref = np.asarray(want)[1:-1, 1:-1]
    d = np.max(np.abs(a - ref))
    worst = max(worst, d / (1.0 + np.max(np.abs(ref))))
assert 0 < worst < 1e-5, f"seam-semantics deviation out of range: {{worst:.3e}}"
print(f"seam-semantics deviation vs wrap solve: {{worst:.3e}}")
"""


@needs_supported_jax  # jax<0.6 interpret mode reorders f64 adds (1-ULP seam)
def test_2d_bitexact_family_invariance_f64_subprocess():
    """The discriminating 2-D check: every (npy, npx) decomposition —
    including (1, 1) — produces the bit-identical f64 trajectory, and
    the family's one documented deviation from the reference wrap
    solve (post- vs pre-friction seam ghosts) stays O(nu*dt)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            textwrap.dedent(_F64_2D_SCRIPT.format(repo=REPO)),
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "bit-exact vs (1,1)" in proc.stdout
    assert "seam-semantics deviation" in proc.stdout
